"""Transformer backbone: block + scan-over-layers stack + causal-LM wrapper.

This is the TPU-native replacement for the reference's fused transformer layer
(``deepspeed/ops/transformer/transformer.py:296`` ``DeepSpeedTransformerLayer`` backed
by ~7.4k LoC of CUDA in ``csrc/transformer/``): on TPU, XLA fuses LN/gelu/bias/dropout
into the matmuls, so the "kernel" is a plain function; the stacked blocks run under
``lax.scan`` (one compiled block, L iterations — compile time O(1) in depth) with
optional ``jax.checkpoint`` rematerialisation standing in for the reference's
activation checkpointing (``runtime/activation_checkpointing/checkpointing.py``).

The block covers the model zoo's variants:
- pre/post-norm (GPT-2/OPT pre-norm, BERT post-norm)
- learned / rotary / ALiBi position encodings (GPT-2 / LLaMA-style / BLOOM)
- MHA with optional GQA (n_kv_heads < n_heads)
- gelu MLP or SwiGLU
- parallel attention+MLP (GPT-J style)
"""

import dataclasses
import typing

import jax
import jax.numpy as jnp

from . import layers as L
from .layers import Param


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    n_layers: int = 12
    n_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    n_kv_heads: typing.Optional[int] = None
    activation: str = "gelu_new"
    norm: str = "layernorm"  # layernorm | rmsnorm
    position_embedding: str = "learned"  # learned | rope | alibi | none
    rope_base: float = 10000.0
    # partial rotary (GPT-J rotary_dim / NeoX rotary_pct): rope the first
    # ``rotary_dim`` dims of each head, pass the rest through. None = full.
    rotary_dim: typing.Optional[int] = None
    rotary_interleaved: bool = False  # GPT-J rotate-every-two pairing
    tie_embeddings: bool = True
    head_bias: bool = False  # untied LM head with bias (GPT-J)
    mlp_bias: typing.Optional[bool] = None  # None -> use_bias (GPT-J: attn
    # projections have no bias but the MLP does)
    embed_layernorm: bool = False  # LN right after the embedding (BLOOM)
    # causal=False -> bidirectional (encoder) attention: BERT-family models
    causal: bool = True
    # segment/token-type embeddings (BERT); 0 disables
    type_vocab_size: int = 0
    # post-norm encoders (BERT) end each block with LN and have no final norm
    final_layernorm: bool = True
    # GPT-Neo-style banded local attention: window size (0 = off) and the
    # per-layer pattern ("global"/"local" strings, cycled over the layers —
    # HF GPTNeoConfig.attention_types expanded)
    local_attention_window: int = 0
    attention_layers: tuple = ()
    # attention logit scale; None = 1/sqrt(head_dim). GPT-Neo uses 1.0
    attn_scale: typing.Optional[float] = None
    use_bias: bool = True
    prenorm: bool = True
    parallel_attn_mlp: bool = False
    # parallel residual with SEPARATE norms: x + attn(ln1 x) + mlp(ln2 x)
    # (GPT-NeoX use_parallel_residual) vs GPT-J's shared ln1 for both
    parallel_norm_split: bool = False
    dropout: float = 0.0
    attn_dropout: float = 0.0
    layernorm_eps: float = 1e-5
    initializer_range: float = 0.02
    scan_layers: bool = True
    # Fused vocab-chunked cross entropy (ops/cross_entropy.py): the LM-head matmul
    # and softmax-CE as one streaming op — the [tokens, vocab] logit matrix is
    # never materialized (fwd or bwd). Big memory + bandwidth win at LLM vocabs.
    fused_ce: bool = True
    fused_ce_chunks: int = 8  # vocab chunks in the streaming CE (tuning knob)
    # "pallas": forward via the streaming Pallas kernel (chunk logits never
    # touch HBM, ops/pallas/cross_entropy.py); backward stays chunked XLA
    fused_ce_impl: str = "xla"  # xla | pallas
    remat: bool = False
    remat_policy: str = "nothing_saveable"  # nothing_saveable | dots_with_no_batch_dims
    compute_dtype: typing.Any = jnp.bfloat16
    attention_impl: str = "xla"  # xla | flash (pallas) | jax_flash (official
    # jax.experimental TPU kernel) | block_sparse (pallas)
    # "bf16": materialize XLA-attention logits/probs in bf16 (fp32
    # normalization sum) — halves the profiled [b,h,s,s] attention HBM
    # traffic; opt-in, measured by the bench sweep ("fp32" = exact default).
    # Applies to attention_impl="xla" only: flash/block_sparse never
    # materialize the logits, which is their whole point.
    attention_logits_dtype: str = "fp32"
    # block_sparse settings (reference sparse_attention_utils.py integration
    # role): pattern name + block size + pattern kwargs
    sparse_pattern: str = "fixed"  # dense|fixed|bigbird|bslongformer|variable
    sparse_block: int = 128
    sparse_pattern_config: typing.Any = None  # dict of pattern kwargs
    attention_interpret: bool = False  # pallas interpret mode (CPU tests)
    # Fused qkv projection (concat the q/k/v kernels, one matmul). The engines
    # force this OFF whenever the ``model`` mesh axis is >1: jnp.concatenate
    # along an axis the operands are sharded on is miscompiled by the SPMD
    # partitioner (jaxlib 0.4.x; a pure sharded concat returns wrong bytes),
    # and under tensor parallelism the three column-parallel matmuls are the
    # standard Megatron form anyway. Fused vs unfused is bitwise-identical
    # per output column, so flipping it never breaks parity pins.
    fused_qkv: bool = True
    # Flash-kernel tile sizes (None = kernel defaults: 256x512 fwd, 256x256
    # bwd). Tuning knobs for tools/bench_attention.py BENCH_BLOCKS sweeps.
    flash_block_q: typing.Any = None
    flash_block_kv: typing.Any = None
    flash_block_q_bwd: typing.Any = None
    flash_block_kv_bwd: typing.Any = None
    # Pipeline parallelism (set by the engine from mesh/config; see parallel/pipeline.py)
    pipeline_stages: int = 1
    pipeline_microbatches: int = 1
    mesh: typing.Any = None  # jax.sharding.Mesh when pipeline_stages > 1
    # Explicit ZeRO-3 gather schedule (set by the engine from
    # zero_optimization.zero3_gather_mode="per_layer"): constrain each scanned
    # block's params to their gathered (data-unsharded) layout INSIDE the layer
    # loop, so the compiler must gather layer-by-layer — bounded live gathered
    # params (the reference coordinator's max_live_parameters semantics,
    # partitioned_param_coordinator.py:230) instead of trusting XLA's schedule.
    zero3_per_layer_gather: bool = False
    zero3_gather_specs: typing.Any = None  # per-block spec tree (no layers dim)
    # "constraint" | "shard_map" (see config.ZeroConfig.zero3_gather_impl);
    # shard_map additionally needs the SHARDED per-block specs below
    zero3_gather_impl: str = "constraint"
    zero3_sharded_specs: typing.Any = None
    # Wire dtype of the shard_map gathers (set by the engine from
    # zero_optimization.zero3_gather_dtype): "compute" (historical — gather
    # at the compute dtype), "fp32" (gather masters, cast after), "bf16" /
    # "fp16" (explicit 16-bit wire), "int8" (ZeRO++ qwZ blockwise-quantized
    # payload + per-block fp32 scales). Masters stay sharded fp32 throughout.
    zero3_gather_dtype: str = "compute"
    zero3_gather_block: int = 256
    # Same discipline for the top-level params (wte / lm_head / ln_f / wpe):
    # {param_name: spec tree} with the data axis stripped. Without this, a
    # ZeRO-3 embedding sharded on its d_model axis (vocab % dp != 0 fallback)
    # propagates INTO the logits matmul and the partitioner partial-sums
    # full-batch logits instead of gathering the weight.
    zero3_toplevel_gather_specs: typing.Any = None
    # Sequence parallelism: shard the sequence dim over the ``seq`` mesh axis with
    # ring attention (set by the engine; see parallel/ring_attention.py)
    sequence_parallel: bool = False
    # Chunk each ring tile's kv axis: peak memory O(s_local * ring_inner_block)
    # instead of O(s_local^2) per ring step. None = whole-tile (short s_local).
    ring_inner_block: typing.Optional[int] = None
    # Serving: route the prefill (q_len == kv_len) through the flash kernel so
    # TTFT never materializes O(s^2) logits. None = auto (TPU backend only);
    # True/False force. Decode steps always keep the dense cached path.
    prefill_flash: typing.Optional[bool] = None
    # Activation quantization (reference compression/basic_layer.py:17 QuantAct
    # via compression.apply_to_model_config): fake-quantize the attention/MLP
    # residual-branch outputs in-graph. 0 = off.
    activation_quant_bits: int = 0
    activation_quant_group: int = 64
    # Explicit per-head width. None = d_model // n_heads; head-pruned models
    # (compression.redundancy_clean) keep the ORIGINAL head width while
    # n_heads shrinks, so attention width n_heads*head_dim < d_model.
    head_dim_override: typing.Optional[int] = None
    # Mixture-of-Experts (see moe/sharded_moe.py; reference deepspeed/moe/)
    n_experts: int = 0            # 0 = dense FFN
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_eval_capacity_factor: float = 0.0  # <=0: drop-free eval (capacity = seq len)
    moe_min_capacity: int = 4
    moe_aux_loss_weight: float = 0.01
    moe_noise_std: float = 0.0
    # Reference TopKGate noisy_gate_policy (sharded_moe.py:398): "jitter"
    # multiplies the gate INPUT by uniform(1±eps); "rsample" adds gumbel noise
    # to the selection logits (gates stay clean). "" = off. Training only.
    moe_noisy_gate_policy: str = ""
    # Random Token Selection (reference top1gating use_rts, sharded_moe.py:220):
    # capacity-overflow drops are decided by random priority, not sequence order
    moe_use_rts: bool = False
    # PR-MoE residual experts (reference moe/layer.py use_residual, arXiv
    # 2201.05596): a dense MLP runs alongside the experts; outputs are blended
    # by a learned 2-way softmax coefficient
    moe_use_residual: bool = False

    def __post_init__(self):
        # a typo here would silently run the exact fp32 path and let a
        # "bf16-logits" benchmark report fp32 numbers — normalize and refuse
        alias = {"bfloat16": "bf16", "float32": "fp32", "f32": "fp32"}
        self.attention_logits_dtype = alias.get(
            str(self.attention_logits_dtype).lower(),
            str(self.attention_logits_dtype).lower())
        if self.attention_logits_dtype not in ("fp32", "bf16"):
            raise ValueError(
                f"attention_logits_dtype must be 'fp32' or 'bf16', got "
                f"{self.attention_logits_dtype!r}")
        # same hazard for the kernel choice: the dispatch falls through to
        # the dense XLA path for anything it doesn't recognize, so a typo'd
        # impl would silently benchmark the wrong kernel (caught live by the
        # bench.py safe-fallback test, 2026-08-01)
        if self.attention_impl not in ("xla", "flash", "jax_flash",
                                       "block_sparse"):
            raise ValueError(
                f"attention_impl must be one of xla|flash|jax_flash|"
                f"block_sparse, got {self.attention_impl!r}")

    @property
    def attn_logits_jnp_dtype(self):
        """None (exact fp32) or the low-precision logits dtype — the single
        switch read by both the training block and the decode path."""
        return jnp.bfloat16 if self.attention_logits_dtype == "bf16" else None

    @property
    def head_dim(self):
        return self.head_dim_override or self.d_model // self.n_heads

    @property
    def kv_heads(self):
        return self.n_kv_heads or self.n_heads

    def num_params(self):
        """Analytic parameter count (embedding + blocks + final norm)."""
        d, f, v = self.d_model, self.d_ff, self.vocab_size
        per_block = 4 * d * d * (self.kv_heads / self.n_heads if self.n_kv_heads else 1.0)
        # more precisely: q:d*q_dim, k,v:d*kv_dim, o:q_dim*d (q_dim < d for
        # head-pruned models with head_dim_override)
        q_dim = self.n_heads * self.head_dim
        kv_dim = self.kv_heads * self.head_dim
        per_block = d * q_dim + 2 * d * kv_dim + q_dim * d
        if self.activation == "swiglu":
            per_block += 3 * d * f
        else:
            per_block += 2 * d * f
        per_block += 4 * d if self.use_bias else 0
        per_block += 2 * d  # two norms (scale+bias counted roughly)
        total = self.n_layers * per_block + v * d
        if self.position_embedding == "learned":
            total += self.max_seq_len * d
        if not self.tie_embeddings:
            total += v * d
        return int(total)


def _norm_init(cfg):
    return L.layernorm_init(cfg.d_model) if cfg.norm == "layernorm" else L.rmsnorm_init(cfg.d_model)


def _norm_apply(cfg, p, x):
    if cfg.norm == "layernorm":
        return L.layernorm_apply(p, x, eps=cfg.layernorm_eps)
    return L.rmsnorm_apply(p, x, eps=cfg.layernorm_eps)


def _mlp_init(rng, cfg):
    k1, k2, k3 = jax.random.split(rng, 3)
    std = cfg.initializer_range
    # GPT-2 scales residual-projection init by 1/sqrt(2L)
    out_std = std / (2.0 * cfg.n_layers) ** 0.5
    bias = cfg.use_bias if cfg.mlp_bias is None else cfg.mlp_bias
    if cfg.activation == "swiglu":
        return {
            "gate": L.linear_init(k1, cfg.d_model, cfg.d_ff, ("embed", "mlp"), bias, std),
            "up": L.linear_init(k2, cfg.d_model, cfg.d_ff, ("embed", "mlp"), bias, std),
            "down": L.linear_init(k3, cfg.d_ff, cfg.d_model, ("mlp", "embed"), bias, out_std),
        }
    return {
        "fc": L.linear_init(k1, cfg.d_model, cfg.d_ff, ("embed", "mlp"), bias, std),
        "proj": L.linear_init(k2, cfg.d_ff, cfg.d_model, ("mlp", "embed"), bias, out_std),
    }


def _mlp_apply(cfg, p, x, tp_manual=False):
    from jax.ad_checkpoint import checkpoint_name

    # tp_manual: column-parallel in (local hidden shard), row-parallel out with
    # an explicit psum over the model axis (used inside manual regions where
    # the SPMD partitioner cannot insert the collective itself, e.g. 1F1B x TP)
    out = (lambda w, h: L.linear_apply_rowparallel(w, h, "model")) \
        if tp_manual else L.linear_apply
    if tp_manual:
        x = L.tp_copy(x, "model")  # completes dL/dx with a backward psum
    if cfg.activation == "swiglu":
        gate = checkpoint_name(L.linear_apply(p["gate"], x), "mlp_hidden")
        up = checkpoint_name(L.linear_apply(p["up"], x), "mlp_hidden")
        return out(p["down"], jax.nn.silu(gate) * up)
    act = L.ACTIVATIONS[cfg.activation]
    h = checkpoint_name(L.linear_apply(p["fc"], x), "mlp_hidden")
    return out(p["proj"], act(h))


def block_init(rng, cfg):
    k_attn, k_mlp = jax.random.split(rng)
    out_std = cfg.initializer_range / (2.0 * cfg.n_layers) ** 0.5
    if cfg.n_experts > 0:
        from ..moe import moe_mlp_init

        mlp = moe_mlp_init(k_mlp, cfg)
    else:
        mlp = _mlp_init(k_mlp, cfg)
    return {
        "ln_1": _norm_init(cfg),
        "attn": L.attention_init(
            k_attn, cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.use_bias,
            cfg.initializer_range, out_stddev=out_std, head_dim=cfg.head_dim,
        ),
        "ln_2": _norm_init(cfg),
        "mlp": mlp,
    }


def _shard_map_gather(cfg, p):
    """Per-leaf explicit all_gather over the ``data`` mesh axis.

    Input leaves carry their ZeRO-3 sharded layout (``zero3_sharded_specs``);
    the output is the gathered layout (``zero3_gather_specs``). Each leaf with
    a data-sharded dim becomes a shard_map island whose body is ONE tiled
    ``jax.lax.all_gather`` — something a sharding constraint cannot pin (the
    partitioner reshards an elementwise op's input to match its constrained
    output, so cast/quantize-then-gather is inexpressible there). Leaves
    without a data shard pass through.

    Wire dtype per ``cfg.zero3_gather_dtype`` (matmul-weight leaves, ndim>=2):
    - ``"compute"`` / 16-bit names: the leaf is gathered at whatever dtype it
      holds (the compute dtype after ``_cast_block_params``; the explicit
      cast-before-wire corner only triggers when the leaf dtype differs,
      e.g. a bf16 wire under fp32 compute);
    - ``"int8"``: ZeRO++-style blockwise-quantized gather
      (``comm/collectives.all_gather_quantized``, per-block fp32 scales,
      straight-through backward);
    - ``"fp32"``: plain gather of the (fp32 master) leaf.
    1-D leaves (biases, norm scales) always gather at their own dtype — they
    are persistence-threshold-sized and norm math wants them exact.
    """
    from ..comm.collectives import all_gather_cast, all_gather_quantized
    from ..parallel.topology import DATA_AXIS

    wire = getattr(cfg, "zero3_gather_dtype", "compute") or "compute"
    wire_dtype = {"compute": cfg.compute_dtype, "bf16": jnp.bfloat16,
                  "fp16": jnp.float16, "fp32": None, "int8": None}[wire]

    def has_data(s):
        return s == DATA_AXIS or (isinstance(s, tuple) and DATA_AXIS in s)

    def one(a, sharded, gathered):
        axes = [i for i, s in enumerate(tuple(sharded)) if has_data(s)]
        if not axes:
            return a
        k = axes[0]
        compressible = a.ndim >= 2 and jnp.issubdtype(a.dtype, jnp.floating)
        if wire == "int8" and compressible:
            body = lambda x: all_gather_quantized(
                x, DATA_AXIS, axis=k, block=cfg.zero3_gather_block,
                out_dtype=a.dtype)
        elif compressible and wire_dtype is not None and a.dtype != wire_dtype:
            body = lambda x: all_gather_cast(
                x, DATA_AXIS, axis=k, wire_dtype=wire_dtype, out_dtype=a.dtype)
        else:
            body = lambda x: jax.lax.all_gather(x, DATA_AXIS, axis=k,
                                                tiled=True)
        f = jax.shard_map(
            body, mesh=cfg.mesh, in_specs=sharded, out_specs=gathered,
            # the varying-mesh-axes inference can't prove an all_gather
            # output replicated; it is (by construction of the collective)
            check_vma=False)
        return f(a)

    return jax.tree_util.tree_map(one, p, cfg.zero3_sharded_specs,
                                  cfg.zero3_gather_specs)


def _cast_block_params(cfg, p):
    """fp32 masters -> compute dtype for the matmul weights. Norm params stay
    fp32 (layernorm computes in fp32 internally anyway); int8 (weight-only-
    quantized) leaves must NOT be cast — their dequant scale lives next to
    them and linear_apply fuses it into the matmul; MoE params cast inside
    moe_mlp_apply (router stays fp32 for stable gating). Idempotent."""
    cast = lambda a: a.astype(cfg.compute_dtype) \
        if jnp.issubdtype(a.dtype, jnp.floating) else a
    return {
        "ln_1": p["ln_1"],
        "ln_2": p["ln_2"],
        "attn": jax.tree_util.tree_map(cast, p["attn"]),
        "mlp": p["mlp"] if cfg.n_experts > 0 else jax.tree_util.tree_map(
            cast, p["mlp"]),
    }


def block_apply(cfg, p, x, mask=None, rope=None, alibi=None, deterministic=True,
                dropout_rng=None, kv_mask=None, seq_manual=False,
                tp_manual=False):
    """One transformer block. x: [batch, seq, d_model] in compute dtype.
    Returns ``(x, aux_loss)`` — aux is the MoE load-balancing term (0 for dense).

    Params arrive as fp32 masters and are cast to the compute dtype here (norm
    params stay fp32 — layernorm computes in fp32 internally anyway)."""
    x = x.astype(cfg.compute_dtype)
    p = _cast_block_params(cfg, p)
    b, s, d = x.shape

    from jax.ad_checkpoint import checkpoint_name

    def attn(h):
        pa = p["attn"]
        if tp_manual:
            h = L.tp_copy(h, "model")  # completes dL/dh with a backward psum
        if "kernel" in pa["q"] and cfg.fused_qkv:
            # one fused qkv matmul (the reference's c_attn / fused qkv gemm):
            # concat of the kernels is a cheap copy next to the [tokens, d] x
            # [d, d+2kv] matmul it enables — wider N keeps the MXU busier than
            # three narrow matmuls. Bitwise-identical per output column.
            # Widths come from the kernels (not cfg) so a tp_manual caller can
            # hand in LOCAL head shards and everything below just works.
            q_w = pa["q"]["kernel"].shape[1]
            kv_w = pa["k"]["kernel"].shape[1]
            wqkv = jnp.concatenate(
                [pa["q"]["kernel"], pa["k"]["kernel"], pa["v"]["kernel"]], axis=1)
            qkv = h @ wqkv
            if "bias" in pa["q"]:
                qkv = qkv + jnp.concatenate(
                    [pa["q"]["bias"], pa["k"]["bias"], pa["v"]["bias"]])
            q, k, v = (qkv[..., :q_w], qkv[..., q_w:q_w + kv_w],
                       qkv[..., q_w + kv_w:])
        else:  # quantized serving path keeps per-matrix dequant
            q = L.linear_apply(pa["q"], h)
            k = L.linear_apply(pa["k"], h)
            v = L.linear_apply(pa["v"], h)
        q = q.reshape(b, s, q.shape[-1] // cfg.head_dim, cfg.head_dim)
        k = k.reshape(b, s, k.shape[-1] // cfg.head_dim, cfg.head_dim)
        v = v.reshape(b, s, v.shape[-1] // cfg.head_dim, cfg.head_dim)
        q = checkpoint_name(q, "q_proj")
        k = checkpoint_name(k, "k_proj")
        v = checkpoint_name(v, "v_proj")
        if rope is not None:
            cos, sin = rope
            q = L.apply_rotary(q, cos, sin, cfg.rotary_dim,
                               cfg.rotary_interleaved)
            k = L.apply_rotary(k, cos, sin, cfg.rotary_dim,
                               cfg.rotary_interleaved)
        n_rep = cfg.n_heads // cfg.kv_heads
        k = L._repeat_kv(k, n_rep)
        v = L._repeat_kv(v, n_rep)
        if cfg.sequence_parallel:
            from ..parallel.ring_attention import (ring_attention,
                                                   ring_attention_manual)

            if seq_manual:
                # already inside the pipeline's manual region over {pipe, seq}
                out = ring_attention_manual(q, k, v, kv_mask=kv_mask,
                                            causal=cfg.causal,
                                            scale=cfg.attn_scale,
                                            inner_block=cfg.ring_inner_block)
            else:
                out = ring_attention(q, k, v, cfg.mesh, kv_mask=kv_mask,
                                     causal=cfg.causal, scale=cfg.attn_scale,
                                     inner_block=cfg.ring_inner_block)
            out = checkpoint_name(out, "attn_out")
            return o_proj(out)
        # pallas paths: plain attention only — padding mask / alibi / dropout
        # force the dense fallback
        kernel_ok = (alibi is None and mask is None
                     and (deterministic or cfg.attn_dropout == 0.0))
        if cfg.attention_impl == "block_sparse" and kernel_ok:
            out = _block_sparse_attn(cfg, s)(q, k, v)
            out = checkpoint_name(out, "attn_out")
            return o_proj(out)
        flash_ok = cfg.attention_impl in ("flash", "jax_flash") and kernel_ok
        if flash_ok:
            if cfg.attention_impl == "jax_flash":
                from ..ops.flash_attention import jax_flash_attention

                out = jax_flash_attention(q, k, v, causal=cfg.causal,
                                          scale=cfg.attn_scale)
            else:
                from ..ops.flash_attention import flash_attention

                out = flash_attention(q, k, v, causal=cfg.causal,
                                      scale=cfg.attn_scale,
                                      block_q=cfg.flash_block_q,
                                      block_kv=cfg.flash_block_kv,
                                      block_q_bwd=cfg.flash_block_q_bwd,
                                      block_kv_bwd=cfg.flash_block_kv_bwd)
        else:
            dense_mask = mask if mask is not None else (
                L.causal_mask(s, s) if cfg.causal else None)
            drop_rng = None
            if not deterministic and dropout_rng is not None and cfg.attn_dropout > 0:
                drop_rng = jax.random.fold_in(dropout_rng, 1)
            out = L.dot_product_attention(
                q, k, v, mask=dense_mask, scale=cfg.attn_scale,
                dropout_rate=0.0 if deterministic else cfg.attn_dropout,
                dropout_rng=drop_rng, alibi_bias=alibi,
                logits_dtype=cfg.attn_logits_jnp_dtype,
            )
        out = checkpoint_name(out, "attn_out")
        return o_proj(out)

    def o_proj(out):
        out = out.reshape(b, s, -1)  # local width under tp_manual
        if tp_manual:
            return L.linear_apply_rowparallel(p["attn"]["o"], out, "model")
        return L.linear_apply(p["attn"]["o"], out)

    def maybe_drop(h, salt):
        if deterministic or cfg.dropout == 0.0 or dropout_rng is None:
            return h
        return L.dropout(jax.random.fold_in(dropout_rng, salt), h, cfg.dropout, False)

    aux = jnp.zeros((), jnp.float32)

    def mlp(h):
        nonlocal aux
        if cfg.n_experts > 0:
            if tp_manual:
                raise NotImplementedError(
                    "MoE layers do not compose with the manual-TP block "
                    "(1F1B x TP); use the GPipe schedule for MoE pipelines")
            from ..moe import moe_mlp_apply

            moe_rng = (jax.random.fold_in(dropout_rng, 4)
                       if dropout_rng is not None else None)
            out, aux_i = moe_mlp_apply(cfg, p["mlp"], h, deterministic=deterministic,
                                       rng=moe_rng)
            aux = aux + aux_i
            return out
        return _mlp_apply(cfg, p["mlp"], h, tp_manual=tp_manual)

    def qact(h):
        # activation fake-quant on the residual branches (QuantAct role,
        # compression/basic_layer.py:17) — dynamic symmetric groupwise range,
        # straight-through gradient; fuses into the surrounding elementwise ops
        if not cfg.activation_quant_bits:
            return h
        from ..ops.quantizer import fake_quantize

        return fake_quantize(h, bits=cfg.activation_quant_bits,
                             group_size=cfg.activation_quant_group)

    if cfg.parallel_attn_mlp:
        h = _norm_apply(cfg, p["ln_1"], x)
        h_mlp = _norm_apply(cfg, p["ln_2"], x) if cfg.parallel_norm_split else h
        return x + maybe_drop(qact(attn(h)), 2) + maybe_drop(qact(mlp(h_mlp)), 3), aux
    elif cfg.prenorm:
        x = x + maybe_drop(qact(attn(_norm_apply(cfg, p["ln_1"], x))), 2)
        x = x + maybe_drop(qact(mlp(_norm_apply(cfg, p["ln_2"], x))), 3)
        return x, aux
    else:
        # post-norm (BERT)
        x = _norm_apply(cfg, p["ln_1"], x + maybe_drop(qact(attn(x)), 2))
        x = _norm_apply(cfg, p["ln_2"], x + maybe_drop(qact(mlp(x)), 3))
        return x, aux


def _remat_policy(cfg):
    """Named checkpoint policies. "minimal" saves only the cheap named activations
    (projections, mlp hidden) and recomputes the O(s^2) attention internals in bwd —
    the reference's "selective activation checkpointing" sweet spot."""
    return {
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "dots_with_no_batch_dims": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        "everything_saveable": jax.checkpoint_policies.everything_saveable,
        "minimal": jax.checkpoint_policies.save_only_these_names(
            # attn_lse: the flash kernel's softmax statistics ([tokens, 1] —
            # trivial HBM) — without it the backward re-runs the whole forward
            # flash kernel per layer just to regenerate the lse residual
            "q_proj", "k_proj", "v_proj", "attn_out", "attn_lse", "mlp_hidden"
        ),
        # minimal minus mlp_hidden: the [tokens, d_ff] save is ~60% of
        # "minimal"'s per-layer HBM; dropping it costs one fc GEMM recompute
        # in the backward — unlocks larger micro-batches on a 16 GB chip
        "minimal_nomlp": jax.checkpoint_policies.save_only_these_names(
            "q_proj", "k_proj", "v_proj", "attn_out", "attn_lse"
        ),
    }[cfg.remat_policy]


def stack_init(rng, cfg):
    """Init all blocks stacked along a leading "layers" dim via vmap — the pytree has
    one leaf per block param with shape [n_layers, ...]. This is what makes
    scan-over-layers (and per-layer ZeRO-3 gathering) natural."""
    rngs = jax.random.split(rng, cfg.n_layers)
    stacked = jax.vmap(lambda r: block_init(r, cfg))(rngs)

    def prepend_layers(param):
        return Param(param.value, ("layers",) + param.axes)

    return jax.tree_util.tree_map(
        prepend_layers, stacked, is_leaf=lambda x: isinstance(x, Param)
    )


_SPARSE_ATTN_CACHE = {}


def _block_sparse_attn(cfg, seq):
    """Config-driven block-sparse attention kernel, cached per shape/pattern
    (layout preprocessing is host-side numpy; the kernel itself is traced).
    The reference reaches this through ``SparseAttentionUtils`` model surgery;
    here it is an ``attention_impl`` choice."""
    from ..ops import sparse_attention as SA
    from ..ops.pallas.block_sparse_attention import BlockSparseAttention

    key = (cfg.sparse_pattern, cfg.sparse_block,
           repr(cfg.sparse_pattern_config), seq, cfg.causal,
           cfg.attn_scale, cfg.attention_interpret)
    if key not in _SPARSE_ATTN_CACHE:
        cls = {
            "dense": SA.DenseSparsityConfig,
            "fixed": SA.FixedSparsityConfig,
            "bigbird": SA.BigBirdSparsityConfig,
            "bslongformer": SA.BSLongformerSparsityConfig,
            "variable": SA.VariableSparsityConfig,
        }[cfg.sparse_pattern]
        sp = cls(block=cfg.sparse_block, **dict(cfg.sparse_pattern_config or {}))
        _SPARSE_ATTN_CACHE[key] = BlockSparseAttention(
            sp, seq, causal=cfg.causal, scale=cfg.attn_scale,
            interpret=cfg.attention_interpret)
    return _SPARSE_ATTN_CACHE[key]


def local_attention_flags(cfg):
    """Per-layer is-local booleans for banded local attention (HF GPT-Neo
    attention_types cycling). The ONE place the pattern expands — shared by
    the training masks and the KV-cache decode path so they cannot drift."""
    pat = cfg.attention_layers or ("global", "local")
    return [pat[i % len(pat)] == "local" for i in range(cfg.n_layers)]


def stack_apply(cfg, stacked_params, x, mask=None, rope=None, alibi=None,
                deterministic=True, dropout_rng=None, kv_mask=None,
                pld_theta=None):
    """Run the L blocks; returns ``(x, aux_loss)``. scan_layers=True: one compiled
    block iterated L times (compile-time constant in depth); False: unrolled python
    loop (better for very shallow nets / per-layer sharding experiments)."""
    if cfg.sequence_parallel:
        if cfg.mesh is None:
            raise ValueError("sequence_parallel requires cfg.mesh to be set")
        if cfg.pipeline_stages > 1 and kv_mask is not None:
            raise NotImplementedError(
                "padding kv_mask not supported with sequence_parallel + pipeline"
            )
        if cfg.position_embedding == "alibi":
            raise NotImplementedError("alibi bias not supported with ring attention")
        if cfg.attn_dropout > 0 and not deterministic:
            raise NotImplementedError("attention dropout not supported with ring attention")
    if cfg.pipeline_stages > 1:
        if cfg.local_attention_window > 0:
            raise NotImplementedError(
                "local_attention_window not supported with pipeline parallelism")
        if pld_theta is not None:
            raise NotImplementedError(
                "progressive layer drop not supported with pipeline parallelism")
        return _pipeline_stack(cfg, stacked_params, x, mask, rope, alibi,
                               deterministic, dropout_rng)

    # GPT-Neo-style banded local attention: per-layer global/local masks
    # (HF GPTNeoConfig.attention_types; reference container containers/gptneo.py)
    local_pattern = None
    local_mask = None
    if cfg.local_attention_window > 0:
        if cfg.sequence_parallel or not cfg.causal:
            raise NotImplementedError(
                "local_attention_window requires a causal, non-SP model")
        s = x.shape[1]
        qi = jnp.arange(s)[:, None]
        ki = jnp.arange(s)[None, :]
        band = (qi >= ki) & (qi - ki < cfg.local_attention_window)
        gmask = mask if mask is not None else L.causal_mask(s, s)
        local_mask = gmask & band
        local_pattern = local_attention_flags(cfg)

    def _constrain(p, specs):
        from jax.sharding import NamedSharding

        return jax.tree_util.tree_map(
            lambda a, s: jax.lax.with_sharding_constraint(
                a, NamedSharding(cfg.mesh, s)),
            p, specs)

    def body(p, h, rng, m):
        # ZeRO-3 per_layer gather, INSIDE the remat region: the bwd
        # re-gathers instead of saving 40 layers of gathered weights as scan
        # residuals (measured +50 GB/chip on the OPT-13B/256 projection when
        # the gather sat outside jax.checkpoint).
        if cfg.zero3_per_layer_gather and cfg.zero3_gather_specs is not None:
            if (cfg.zero3_gather_impl == "shard_map"
                    and cfg.zero3_sharded_specs is not None):
                # explicit all_gather island with the wire dtype pinned
                # BEFORE the collective (compute-dtype cast or int8
                # quantization) — half/quarter the wire of gathering the
                # fp32 master (which is all the constraint impl below can
                # express — the partitioner reshards an elementwise op's
                # input to match its constrained output, and both
                # jax.sharding.reshard and an optimization_barrier broke
                # Shardy propagation for the surrounding scan)
                if cfg.zero3_gather_dtype == "fp32":
                    # explicit-but-fp32 wire: gather the masters, cast after
                    p = _cast_block_params(cfg, _shard_map_gather(cfg, p))
                else:
                    p = _shard_map_gather(cfg, _cast_block_params(cfg, p))
            else:
                # "constraint": fp32-sized gather wire, a known 2x
                # (PARITY.md known gaps); overlap headroom absorbs it
                # (scale_projection: 3.3x at OPT-13B/v4-256 micro=1)
                p = _constrain(_cast_block_params(cfg, p),
                               cfg.zero3_gather_specs)
        return block_apply(
            cfg, p, h, mask=m, rope=rope, alibi=alibi,
            deterministic=deterministic, dropout_rng=rng, kv_mask=kv_mask,
        )

    if cfg.remat:
        body = jax.checkpoint(body, policy=_remat_policy(cfg), static_argnums=())

    def pld_select(i, h_new, h_prev, aux_i, rng_i):
        """Progressive layer drop (reference ``progressive_layer_drop.py``):
        keep layer i with prob 1 - (i/L)(1 - theta); a dropped layer passes
        the residual stream through untouched (no rescale, as in the paper).
        """
        if pld_theta is None or deterministic or dropout_rng is None:
            return h_new, aux_i
        keep_p = 1.0 - (i.astype(jnp.float32) / cfg.n_layers) * (1.0 - pld_theta)
        keep = jax.random.bernoulli(jax.random.fold_in(rng_i, 9), keep_p)
        return (jnp.where(keep, h_new, h_prev),
                jnp.where(keep, aux_i, jnp.zeros_like(aux_i)))

    aux = jnp.zeros((), jnp.float32)
    # Banded local attention scans too: the per-layer global/local choice is a
    # traced boolean scanned alongside the stacked weights, selecting between
    # the two precomputed [s, s] masks in-graph — compile time stays constant
    # in depth. Only pallas attention keeps the unrolled loop (an explicit
    # mask forces the kernels' dense fallback, so the python-level mask=None
    # on global layers is what keeps them kernel-eligible there).
    unrolled = not cfg.scan_layers or (
        local_pattern is not None
        and cfg.attention_impl in ("flash", "jax_flash", "block_sparse"))
    if unrolled:
        for i in range(cfg.n_layers):
            p_i = jax.tree_util.tree_map(lambda a: a[i], stacked_params)
            rng_i = jax.random.fold_in(dropout_rng, i) if dropout_rng is not None else None
            m_i = local_mask if (local_pattern is not None and local_pattern[i]) \
                else mask
            h_new, aux_i = body(p_i, x, rng_i, m_i)
            x, aux_i = pld_select(jnp.asarray(i), h_new, x, aux_i, rng_i)
            aux = aux + aux_i
        return x, aux

    def scan_step(h, i, aux, p, m_i):
        rng_i = jax.random.fold_in(dropout_rng, i) if dropout_rng is not None else None
        h_new, aux_i = body(p, h, rng_i, m_i)
        h, aux_i = pld_select(i, h_new, h, aux_i, rng_i)
        return h, i + 1, aux + aux_i

    if local_pattern is not None:
        # gmask was built alongside local_mask above; the per-layer choice is
        # a traced flag scanned with the weights
        def scan_fn(carry, xs):
            p, is_local = xs
            return scan_step(*carry, p, jnp.where(is_local, local_mask, gmask)), None

        xs_in = (stacked_params, jnp.asarray(local_pattern))
    else:
        def scan_fn(carry, xs):
            return scan_step(*carry, xs, mask), None

        xs_in = stacked_params

    (x, _, aux), _ = jax.lax.scan(
        scan_fn, (x, jnp.zeros((), jnp.int32), aux), xs_in
    )
    return x, aux


def _pipeline_stack(cfg, stacked_params, x, mask, rope, alibi, deterministic,
                    dropout_rng):
    """Pipeline-parallel path of ``stack_apply`` (see parallel/pipeline.py)."""
    from ..parallel.pipeline import pipeline_stack_apply

    if cfg.mesh is None:
        raise ValueError("pipeline_stages > 1 requires cfg.mesh to be set")

    # Batched side inputs must travel with their microbatch through the pipe
    # rotation; unbatched ones ride the closure. Shapes from CausalLM.apply:
    # mask [b,1,q,kv] (causal-only masks are [1,1,q,kv]), rope cos/sin [b,s,hd/2].
    b = x.shape[0]
    seq_manual = cfg.sequence_parallel
    side = {}
    if mask is not None and mask.ndim == 4 and mask.shape[0] == b and b > 1:
        if seq_manual:
            raise NotImplementedError(
                "batched attention masks not supported with sequence_parallel "
                "+ pipeline (ring attention computes causal masking itself)")
        side["mask"] = mask
    if rope is not None and rope[0].ndim == 3 and rope[0].shape[0] == b:
        side["rope_cos"], side["rope_sin"] = rope

    def pipe_block(p, h, side_mb, rng):
        m = side_mb["mask"] if "mask" in side_mb else mask
        r = ((side_mb["rope_cos"], side_mb["rope_sin"])
             if "rope_cos" in side_mb else rope)
        return block_apply(cfg, p, h, mask=m, rope=r, alibi=alibi,
                           deterministic=deterministic, dropout_rng=rng,
                           seq_manual=seq_manual)

    if cfg.remat:
        pipe_block = jax.checkpoint(pipe_block, policy=_remat_policy(cfg))

    def block_fn(p, h, side_mb, layer_idx, mb_idx):
        # fold in both layer and microbatch so dropout masks are independent
        # across the accumulation window (non-pipeline grad-accum draws a fresh
        # step rng per micro-step)
        rng_i = None
        if dropout_rng is not None:
            rng_i = jax.random.fold_in(
                jax.random.fold_in(dropout_rng, layer_idx), mb_idx
            )
        return pipe_block(p, h, side_mb, rng_i)

    return pipeline_stack_apply(
        cfg, stacked_params, x, mesh=cfg.mesh,
        n_microbatches=cfg.pipeline_microbatches, block_fn=block_fn, side=side,
        seq_manual=seq_manual,
    )


class CausalLM:
    """Decoder-only LM over the generic backbone. The concrete model families
    (GPT-2, OPT, BLOOM, LLaMA-style) are TransformerConfig presets in
    ``models/registry.py`` — they differ only in config, not code."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    def _gather_toplevel(self, params):
        """ZeRO-3 per_layer mode: constrain top-level params to their gathered
        (data-unsharded) layout before use — gather-weights-compute-release,
        mirroring the per-block constraint inside the layer scan."""
        cfg = self.config
        specs = getattr(cfg, "zero3_toplevel_gather_specs", None)
        if not (getattr(cfg, "zero3_per_layer_gather", False) and specs):
            return params
        from jax.sharding import NamedSharding

        out = dict(params)
        for k, sub in specs.items():
            if k in out:
                out[k] = jax.tree_util.tree_map(
                    lambda a, s: jax.lax.with_sharding_constraint(
                        a, NamedSharding(cfg.mesh, s)),
                    out[k], sub)
        return out

    # -- init ---------------------------------------------------------------------
    def init(self, rng):
        cfg = self.config
        k_emb, k_pos, k_blocks, k_head = jax.random.split(rng, 4)
        params = {
            "wte": L.embedding_init(k_emb, cfg.vocab_size, cfg.d_model, cfg.initializer_range),
            "blocks": stack_init(k_blocks, cfg),
        }
        if cfg.final_layernorm:
            params["ln_f"] = _norm_init(cfg)
        if cfg.position_embedding == "learned":
            params["wpe"] = {
                "weight": Param(
                    L.normal_init(k_pos, (cfg.max_seq_len, cfg.d_model), cfg.initializer_range),
                    ("seq_table", "embed"),
                )
            }
        if cfg.type_vocab_size:
            params["wtt"] = {
                "weight": Param(
                    L.normal_init(jax.random.fold_in(k_pos, 1),
                                  (cfg.type_vocab_size, cfg.d_model),
                                  cfg.initializer_range),
                    (None, "embed"),
                )
            }
        if cfg.embed_layernorm:
            params["ln_emb"] = _norm_init(cfg)
        if not cfg.tie_embeddings:
            params["lm_head"] = L.linear_init(
                k_head, cfg.d_model, cfg.vocab_size, ("embed", "vocab"),
                bias=cfg.head_bias, stddev=cfg.initializer_range,
            )
        return params

    # -- forward ------------------------------------------------------------------
    def backbone(self, params, input_ids, positions=None, attention_mask=None,
                 deterministic=True, dropout_rng=None, token_type_ids=None,
                 pld_theta=None):
        """Embedding + blocks + final norm -> ([batch, seq, d_model], aux)."""
        cfg = self.config
        params = self._gather_toplevel(params)
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))

        x = L.embedding_apply(params["wte"], input_ids, cfg.compute_dtype)
        if cfg.position_embedding == "learned":
            x = x + jnp.take(params["wpe"]["weight"].astype(cfg.compute_dtype), positions, axis=0)
        if cfg.type_vocab_size and token_type_ids is not None:
            x = x + jnp.take(params["wtt"]["weight"].astype(cfg.compute_dtype),
                             token_type_ids, axis=0)
        if cfg.embed_layernorm:
            x = _norm_apply(cfg, params["ln_emb"], x)

        # mask=None means "plain causal (or fully bidirectional for encoders)"
        # — lets the flash kernel run; an explicit padding mask forces the
        # dense path. Under sequence parallelism the padding mask stays in
        # [b, s] form and rides the ring with K/V.
        mask = None
        kv_mask = None
        if attention_mask is not None:
            if cfg.sequence_parallel:
                kv_mask = attention_mask.astype(bool)
            else:
                pad = attention_mask[:, None, None, :].astype(bool)
                mask = (L.causal_mask(s, s) & pad) if cfg.causal else \
                    jnp.broadcast_to(pad, (b, 1, s, s))

        rope = None
        if cfg.position_embedding == "rope":
            rope = L.rotary_embedding(positions, cfg.rotary_dim or cfg.head_dim,
                                      cfg.rope_base)
        alibi = None
        if cfg.position_embedding == "alibi":
            alibi = L.alibi_bias(cfg.n_heads, s, s)

        x, aux = stack_apply(cfg, params["blocks"], x, mask=mask, rope=rope,
                             alibi=alibi, deterministic=deterministic,
                             dropout_rng=dropout_rng, kv_mask=kv_mask,
                             pld_theta=pld_theta)
        if cfg.final_layernorm:
            x = _norm_apply(cfg, params["ln_f"], x)
        return x, aux

    def head(self, params, x):
        """Hidden states -> logits [batch, seq, vocab] (compute dtype)."""
        params = self._gather_toplevel(params)
        if self.config.tie_embeddings:
            return L.embedding_attend(params["wte"], x)
        return L.linear_apply(params["lm_head"], x)

    def head_ce(self, params, x, labels):
        """Cross entropy from post-final-norm hidden states; picks the fused
        vocab-chunked path or the materialized-logits path per config. ``params``
        needs only the head leaves (wte / lm_head), so pipeline stages can pass
        a head-only subtree."""
        cfg = self.config
        params = self._gather_toplevel(params)
        if cfg.fused_ce:
            from ..ops.cross_entropy import fused_cross_entropy

            if cfg.tie_embeddings:
                emb, bias = params["wte"]["weight"], None
            else:
                emb = params["lm_head"]["kernel"].T
                bias = params["lm_head"].get("bias")  # GPT-J biased head
            return fused_cross_entropy(
                x.reshape(-1, cfg.d_model), emb, labels.reshape(-1), bias,
                n_chunks=cfg.fused_ce_chunks, impl=cfg.fused_ce_impl,
                interpret=cfg.attention_interpret)
        return cross_entropy_loss(self.head(params, x), labels)

    def apply(self, params, input_ids, positions=None, attention_mask=None,
              deterministic=True, dropout_rng=None, return_aux=False):
        """input_ids: [batch, seq] int32 -> logits [batch, seq, vocab] (compute
        dtype); with ``return_aux`` also the MoE auxiliary loss."""
        x, aux = self.backbone(params, input_ids, positions=positions,
                               attention_mask=attention_mask,
                               deterministic=deterministic, dropout_rng=dropout_rng)
        logits = self.head(params, x)
        return (logits, aux) if return_aux else logits

    # -- loss ---------------------------------------------------------------------
    def loss(self, params, batch, deterministic=True, dropout_rng=None,
             pld_theta=None):
        """Next-token cross entropy. batch: {input_ids, labels?, attention_mask?};
        labels default to input_ids shifted; label -100 = ignored (HF convention).
        ``pld_theta``: traced progressive-layer-drop keep parameter (engine)."""
        cfg = self.config
        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [input_ids[:, 1:], jnp.full_like(input_ids[:, :1], -100)], axis=1
            )
        x, aux = self.backbone(
            params, input_ids, attention_mask=batch.get("attention_mask"),
            positions=batch.get("position_ids"), deterministic=deterministic,
            dropout_rng=dropout_rng, pld_theta=pld_theta,
        )
        return self.head_ce(params, x, labels) + aux


class MaskedLM(CausalLM):
    """Encoder (BERT-family) over the same backbone: bidirectional attention
    (``causal=False``), post-norm blocks, token-type embeddings, and the BERT
    MLM prediction head (dense + gelu + LN + tied decoder with its own bias —
    the reference's kernel-accelerated BERT training target,
    ``docs/_tutorials/bert-pretraining.md`` / ``tests/unit/modeling.py``).

    batch: {input_ids, labels, attention_mask?, token_type_ids?}; labels use
    the HF convention (-100 everywhere except the masked positions).
    """

    def init(self, rng):
        cfg = self.config
        if cfg.causal:
            raise ValueError("MaskedLM requires causal=False (a bert_config "
                             "preset from models/registry.py)")
        params = super().init(rng)
        k1, k2 = jax.random.split(jax.random.fold_in(rng, 17))
        params["mlm_transform"] = L.linear_init(
            k1, cfg.d_model, cfg.d_model, ("embed", None),
            stddev=cfg.initializer_range)
        params["mlm_ln"] = L.layernorm_init(cfg.d_model)
        # decoder reuses wte (tied) but keeps a separate output bias
        params["mlm_bias"] = {
            "bias": Param(jnp.zeros((cfg.vocab_size,)), ("vocab",))}
        return params

    def _mlm_transform(self, params, x):
        cfg = self.config
        h = L.linear_apply(params["mlm_transform"], x)
        h = L.ACTIVATIONS[cfg.activation](h)  # BERT: exact-erf gelu
        return L.layernorm_apply(params["mlm_ln"], h, eps=cfg.layernorm_eps)

    def head(self, params, x):
        params = self._gather_toplevel(params)
        h = self._mlm_transform(params, x)
        logits = L.embedding_attend(params["wte"], h)
        return logits + params["mlm_bias"]["bias"].astype(logits.dtype)

    def head_ce(self, params, x, labels):
        cfg = self.config
        params = self._gather_toplevel(params)
        h = self._mlm_transform(params, x)
        if cfg.fused_ce:
            from ..ops.cross_entropy import fused_cross_entropy

            return fused_cross_entropy(
                h.reshape(-1, cfg.d_model), params["wte"]["weight"],
                labels.reshape(-1), params["mlm_bias"]["bias"],
                n_chunks=cfg.fused_ce_chunks, impl=cfg.fused_ce_impl,
                interpret=cfg.attention_interpret)
        logits = L.embedding_attend(params["wte"], h) \
            + params["mlm_bias"]["bias"].astype(cfg.compute_dtype)
        return cross_entropy_loss(logits, labels)

    def apply(self, params, input_ids, positions=None, attention_mask=None,
              deterministic=True, dropout_rng=None, return_aux=False,
              token_type_ids=None):
        cfg = self.config
        if token_type_ids is None and cfg.type_vocab_size:
            token_type_ids = jnp.zeros_like(input_ids)  # HF default segment 0
        x, aux = self.backbone(params, input_ids, positions=positions,
                               attention_mask=attention_mask,
                               token_type_ids=token_type_ids,
                               deterministic=deterministic,
                               dropout_rng=dropout_rng)
        logits = self.head(params, x)
        return (logits, aux) if return_aux else logits

    def loss(self, params, batch, deterministic=True, dropout_rng=None,
             pld_theta=None):
        """Masked-token cross entropy; no label shifting (denoising, not AR)."""
        if "labels" not in batch:
            raise ValueError("MaskedLM.loss needs explicit 'labels' "
                             "(-100 outside masked positions)")
        token_type_ids = batch.get("token_type_ids")
        if token_type_ids is None and self.config.type_vocab_size:
            token_type_ids = jnp.zeros_like(batch["input_ids"])
        x, aux = self.backbone(
            params, batch["input_ids"],
            attention_mask=batch.get("attention_mask"),
            positions=batch.get("position_ids"),
            token_type_ids=token_type_ids,
            deterministic=deterministic, dropout_rng=dropout_rng,
            pld_theta=pld_theta,
        )
        return self.head_ce(params, x, batch["labels"]) + aux


class TextEncoder(CausalLM):
    """Headless conditioning encoder (CLIP text model shape): causal prenorm
    transformer whose OUTPUT is the final hidden states, consumed by a
    diffusion UNet's cross-attention (reference container:
    ``module_inject/containers/clip.py`` for the stable-diffusion text
    encoder). No LM head; ``tie_embeddings`` keeps init head-free."""

    def apply(self, params, input_ids, positions=None, attention_mask=None,
              deterministic=True, dropout_rng=None, return_aux=False):
        x, aux = self.backbone(params, input_ids, positions=positions,
                               attention_mask=attention_mask,
                               deterministic=deterministic,
                               dropout_rng=dropout_rng)
        return (x, aux) if return_aux else x  # hidden states, not logits

    def loss(self, params, batch, deterministic=True, dropout_rng=None):
        raise NotImplementedError(
            "TextEncoder is a conditioning encoder (no LM objective); train "
            "the underlying backbone as a CausalLM if you need an LM loss")


def cross_entropy_loss(logits, labels, ignore_index=-100):
    """Token-mean cross entropy in fp32; -100 labels masked out."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    token_ll = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (logz - token_ll) * valid
    return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1)
