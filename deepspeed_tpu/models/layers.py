"""Core neural-net layers as pure functions.

The reference builds on torch ``nn.Module``; the TPU-native design keeps models as
pure ``init``/``apply`` function pairs over parameter pytrees. Every parameter carries
*logical axis names* (a tuple of strings, one per dim) in a parallel "axes" pytree —
the sharding layer (``parallel/sharding.py``) maps logical names to mesh axes per
parallelism config. This replaces the reference's module-walking machinery
(``module_inject/replace_module.py``) with data: resharding a model = changing the
rule table, not surgically editing modules.

Logical axis vocabulary (used across the model zoo):
    "vocab"   — vocabulary dim of embeddings / LM head
    "embed"   — model (residual) width
    "mlp"     — feed-forward hidden width (TP-sharded: column parallel in, row out)
    "heads"   — attention heads * head_dim flattened width (TP-sharded)
    "kv"      — kv heads width for GQA/MQA
    "layers"  — scan dim over stacked transformer blocks
    None      — never sharded (biases, layernorm scales use ("embed",) etc.)

Compute dtype: params are stored in fp32 (the master copy; reference
``runtime/fp16/fused_optimizer.py`` keeps the same split) and cast to the compute
dtype (bf16/fp16) at apply time.
"""

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


@dataclasses.dataclass
class Param:
    """A parameter leaf paired with its logical axes.

    Registered as a pytree node (value = child, axes = static aux) so transforms
    like ``vmap`` over block init carry the axes metadata through untouched.
    """

    value: jnp.ndarray
    axes: tuple


jax.tree_util.register_pytree_node(
    Param,
    lambda p: ((p.value,), p.axes),
    lambda axes, children: Param(children[0], axes),
)


def split_params_axes(tree):
    """Split a tree of Param into (values, axes) trees."""
    is_param = lambda x: isinstance(x, Param)
    values = jax.tree_util.tree_map(lambda p: p.value, tree, is_leaf=is_param)
    axes = jax.tree_util.tree_map(lambda p: p.axes, tree, is_leaf=is_param)
    return values, axes


# ---------------------------------------------------------------------------------
# Initializers
# ---------------------------------------------------------------------------------
def normal_init(rng, shape, stddev=0.02, dtype=jnp.float32):
    return jax.random.normal(rng, shape, dtype) * stddev


def zeros_init(shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones_init(shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


# ---------------------------------------------------------------------------------
# Linear / embedding / layernorm
# ---------------------------------------------------------------------------------
def linear_init(rng, in_dim, out_dim, axes, bias=True, stddev=0.02):
    p = {"kernel": Param(normal_init(rng, (in_dim, out_dim), stddev), axes)}
    if bias:
        p["bias"] = Param(zeros_init((out_dim,)), (axes[-1],))
    return p


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_copy(x, axis):
    """Identity forward / psum backward over ``axis`` — the conjugate of the
    row-parallel psum, applied to the INPUT of column-parallel matmuls inside a
    manual-TP region (Megatron's f operator): ``d(x @ W_local)/dx`` is a
    partial sum, and this is where it completes."""
    return x


def _psum_f32(x, axis):
    # bf16/f16 all-reduces miscompile in partial-manual regions ("Invalid
    # binary instruction opcode copy", same workaround as parallel/pipeline.py)
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return jax.lax.psum(x.astype(jnp.float32), axis).astype(x.dtype)
    return jax.lax.psum(x, axis)


def _tp_copy_fwd(x, axis):
    return x, None


def _tp_copy_bwd(axis, _, g):
    return (_psum_f32(g, axis),)


tp_copy.defvjp(_tp_copy_fwd, _tp_copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_reduce(x, axis):
    """psum forward / identity backward — the row-parallel output reduction
    (Megatron's g operator). A bare ``lax.psum`` is WRONG here under legacy
    (check_vma=False) shard_map: its transpose is another psum, which doubles
    every upstream cotangent."""
    return _psum_f32(x, axis)


def _tp_reduce_fwd(x, axis):
    return _psum_f32(x, axis), None


def _tp_reduce_bwd(axis, _, g):
    return (g,)


tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)


def linear_apply_rowparallel(p, x, axis):
    """Row-parallel linear INSIDE a manual region over ``axis``: the input's
    feature dim is a local shard, the matmul produces a partial sum,
    ``tp_reduce`` completes it, and the bias is added once after (the
    reference's ``RowParallelLinear`` ordering, ``compression/basic_layer.py:802``)."""
    y = x @ p["kernel"].astype(x.dtype)
    y = tp_reduce(y, axis)
    if "bias" in p:
        y = y + p["bias"].astype(y.dtype)
    return y


# Pallas dequant-matmul dispatch switch. The inference engine turns the
# kernel OFF for tensor-parallel serving: an opaque pallas_call has no
# sharding rule, so under tp > 1 the SPMD partitioner would replicate the
# model-axis-sharded quantized weight on every device — erasing exactly the
# per-device HBM win quantization exists for (the XLA dequant+dot path
# partitions correctly). Set via set_quantized_matmul_enabled before trace.
_QMM_MODE = "on"  # "on" | "off" | "interpret" (interpret = CPU-testable)


def set_quantized_matmul_enabled(flag):
    global _QMM_MODE
    _QMM_MODE = "on" if flag else "off"


def _quantized_matmul_or_none(p, x, bits):
    """Fused Pallas dequant-matmul when eligible — the packed weight is what
    streams from HBM; unpack, group-scale, and the MXU dot happen per-tile
    in VMEM. Measured necessity: XLA does NOT fuse the int4 nibble unpack
    into the matmul (2026-08-01 serving bench: int4 decode 3-4x slower than
    bf16), so dequantizing outside the kernel round-trips the full-size
    weight through HBM every decode step."""
    import os

    mode = os.environ.get("DS_TPU_QMM", _QMM_MODE)
    interpret = mode == "interpret"
    if mode == "off" or mode == "0" \
            or (not interpret and jax.default_backend() != "tpu"):
        return None
    key = "kernel_q4" if bits == 4 else "kernel_q"
    q = p[key]
    if q.ndim != 2:
        return None
    xm = x.reshape(-1, x.shape[-1])
    if xm.shape[0] > 2048:
        return None  # prefill-sized token counts: VMEM accumulator too large
    from ..ops.pallas.quantized_matmul import quantized_matmul

    y = quantized_matmul(xm, q, p["kernel_scale"], bits=bits,
                         interpret=interpret)
    if y is None:
        return None
    return y.reshape(x.shape[:-1] + (y.shape[-1],))


def linear_apply(p, x, compute_dtype=None):
    if "kernel_q4" in p or "kernel_q" in p:
        bits = 4 if "kernel_q4" in p else 8
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        y = _quantized_matmul_or_none(p, x, bits=bits)
        if y is not None:
            if "bias" in p:
                y = y + p["bias"].astype(y.dtype)
            return y
        # XLA fallback (CPU / tp>1 / non-tileable shapes): unpack + dequant
        # and let XLA fuse what it can into the matmul; the weight still
        # streams from HBM at its quantized width when fusion succeeds
        from ..ops.quantizer import dequantize_per_channel, unpack_int4

        qk = unpack_int4(p["kernel_q4"]) if bits == 4 else p["kernel_q"]
        kernel = dequantize_per_channel(qk, p["kernel_scale"], x.dtype)
    else:
        kernel = p["kernel"]
        if compute_dtype is not None:
            kernel = kernel.astype(compute_dtype)
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
    y = x @ kernel
    if "bias" in p:
        b = p["bias"].astype(y.dtype) if compute_dtype is not None else p["bias"]
        y = y + b
    return y


def embedding_init(rng, vocab_size, embed_dim, stddev=0.02):
    return {"weight": Param(normal_init(rng, (vocab_size, embed_dim), stddev), ("vocab", "embed"))}


def embedding_apply(p, ids, compute_dtype=None):
    w = p["weight"]
    if compute_dtype is not None:
        w = w.astype(compute_dtype)
    return jnp.take(w, ids, axis=0)


def embedding_attend(p, x):
    """Tied LM head: logits = x @ E^T."""
    return x @ p["weight"].astype(x.dtype).T


def layernorm_init(dim):
    return {
        "scale": Param(ones_init((dim,)), ("embed",)),
        "bias": Param(zeros_init((dim,)), ("embed",)),
    }


def layernorm_apply(p, x, eps=1e-5):
    """LayerNorm computed in fp32 regardless of compute dtype (the reference's fused
    kernels do the same internally; csrc/transformer/normalize_kernels.cu)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(dtype)


def rmsnorm_init(dim):
    return {"scale": Param(ones_init((dim,)), ("embed",))}


def rmsnorm_apply(p, x, eps=1e-6):
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + eps)
    return (y * p["scale"]).astype(dtype)


# ---------------------------------------------------------------------------------
# Activations (reference: csrc/transformer/gelu_kernels.cu — XLA fuses these)
# ---------------------------------------------------------------------------------
ACTIVATIONS = {
    # jax.nn.gelu defaults to the tanh approximation — matches BLOOM/GPT-2's
    # "gelu"; HF models whose gelu is the exact erf form map to gelu_exact.
    "gelu": jax.nn.gelu,
    "gelu_exact": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "quick_gelu": lambda x: x * jax.nn.sigmoid(1.702 * x),  # CLIP
    "swiglu": None,  # handled structurally in the MLP
}


# ---------------------------------------------------------------------------------
# Attention
# ---------------------------------------------------------------------------------
def attention_init(rng, embed_dim, n_heads, n_kv_heads=None, bias=True, stddev=0.02,
                   out_stddev=None, head_dim=None):
    """QKV + output projection. Fused qkv as one matrix (the reference's inference
    kernels fuse qkv gemm the same way; csrc/transformer/inference).

    ``head_dim`` defaults to embed_dim // n_heads; head-pruned models pass the
    original width explicitly, making q/o width n_heads*head_dim < embed_dim."""
    n_kv_heads = n_kv_heads or n_heads
    head_dim = head_dim or embed_dim // n_heads
    q_dim = n_heads * head_dim
    kv_dim = n_kv_heads * head_dim
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    return {
        "q": linear_init(k1, embed_dim, q_dim, ("embed", "heads"), bias, stddev),
        "k": linear_init(k2, embed_dim, kv_dim, ("embed", "kv"), bias, stddev),
        "v": linear_init(k3, embed_dim, kv_dim, ("embed", "kv"), bias, stddev),
        "o": linear_init(k4, q_dim, embed_dim, ("heads", "embed"), bias,
                         out_stddev or stddev),
    }


def _repeat_kv(x, n_rep):
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def dot_product_attention(q, k, v, mask=None, scale=None, dropout_rate=0.0,
                          dropout_rng=None, alibi_bias=None,
                          logits_dtype=None):
    """Plain XLA attention: softmax(q k^T / sqrt(d)) v, fp32 softmax.

    The reference's fused softmax/dropout kernels (csrc/transformer/softmax_kernels.cu,
    dropout_kernels.cu) are XLA fusions here; the flash/pallas path lives in
    ``ops/flash_attention.py`` and is selected by the model config.
    q,k,v: [batch, seq, heads, head_dim]

    ``logits_dtype=jnp.bfloat16`` materializes the [b,h,q,kv] logits/probs in
    bf16 (HALF the attention HBM traffic — the profiled single-chip MFU
    bottleneck at the bench shape) with a max-subtracted exp and an fp32
    normalization sum, so only the per-element mantissa rounds; default fp32
    is bit-identical to before.
    """
    head_dim = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(head_dim)
    ldt = jnp.float32 if logits_dtype is None else jnp.dtype(logits_dtype)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=ldt) * jnp.asarray(scale, ldt)
    logits = checkpoint_name(logits, "attn_logits")
    if alibi_bias is not None:
        logits = logits + alibi_bias.astype(ldt)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(ldt).min)
    if ldt == jnp.float32:
        probs = jax.nn.softmax(logits, axis=-1)
    else:
        # stable low-precision softmax: bf16 exp (keeps the [q,kv] tensor
        # narrow in HBM); the row max is exact in any dtype (order-stable,
        # no accumulation) — only the normalization SUM needs fp32. The
        # normalization multiplies by the fp32-accumulated reciprocal ROUNDED
        # to ldt, so no full-size fp32 [b,h,q,kv] intermediate exists even
        # inside fusions (pinned by test_bf16_attention_logits_hlo_buffer_
        # dtype); the reciprocal's rounding error (~2^-8 relative) is below
        # the bf16 output rounding already accepted on every element
        m = jnp.max(logits, axis=-1, keepdims=True)
        e = jnp.exp(logits - m)
        denom = jnp.sum(e.astype(jnp.float32), axis=-1, keepdims=True)
        probs = e * (1.0 / denom).astype(ldt)
    probs = checkpoint_name(probs, "attn_probs")
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def causal_mask(q_len, kv_len, dtype=jnp.bool_):
    """[1, 1, q, kv] lower-triangular mask aligned to the end of the kv window."""
    q_idx = jnp.arange(q_len)[:, None]
    kv_idx = jnp.arange(kv_len)[None, :]
    offset = kv_len - q_len
    return (kv_idx <= q_idx + offset)[None, None, :, :].astype(dtype)


def rotary_embedding(positions, head_dim, base=10000.0, dtype=jnp.float32):
    """RoPE cos/sin tables (reference csrc/transformer/inference/apply_rotary_pos_emb.cu)."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., head_dim/2]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rotary(x, cos, sin, rotary_dim=None, interleaved=False):
    """x: [batch, seq, heads, head_dim]; cos/sin: [batch, seq, rd/2].

    ``rotary_dim``: rotate only the first rd dims of each head (GPT-J/NeoX
    partial rotary), pass the remainder through unchanged.
    ``interleaved``: rotate (x0,x1),(x2,x3),... pairs (GPT-J rotate-every-two)
    instead of the half-split (x_i, x_{i+d/2}) convention (NeoX/LLaMA)."""
    if rotary_dim is not None and rotary_dim < x.shape[-1]:
        x_rot, x_pass = x[..., :rotary_dim], x[..., rotary_dim:]
        return jnp.concatenate(
            [apply_rotary(x_rot, cos, sin, interleaved=interleaved), x_pass],
            axis=-1)
    cos = cos[:, :, None, :].astype(x.dtype)
    sin = sin[:, :, None, :].astype(x.dtype)
    if interleaved:
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
        return out.reshape(x.shape)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def alibi_slopes(n_heads):
    """ALiBi slopes (reference inference kernels support alibi for BLOOM)."""
    def pow2slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        return jnp.asarray(pow2slopes(n_heads))
    closest = 2 ** math.floor(math.log2(n_heads))
    base = pow2slopes(closest)
    extra = pow2slopes(2 * closest)[0::2][: n_heads - closest]
    return jnp.asarray(base + extra)


def alibi_bias(n_heads, q_len, kv_len):
    """[1, heads, q, kv] additive bias."""
    slopes = alibi_slopes(n_heads)  # [h]
    kv_idx = jnp.arange(kv_len)[None, :]
    q_idx = jnp.arange(q_len)[:, None] + (kv_len - q_len)
    dist = kv_idx - q_idx  # <= 0 within causal window
    return (slopes[:, None, None] * dist[None, :, :])[None].astype(jnp.float32)


def dropout(rng, x, rate, deterministic):
    if deterministic or rate == 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return x * keep / (1.0 - rate)
