"""Stable-Diffusion (diffusers-format) checkpoint import for the spatial models.

Reference ``model_implementations/diffusers/unet.py:73`` +
``module_inject/replace_module.py:184``: the reference injects kernels into a
live diffusers ``UNet2DConditionModel``/``AutoencoderKL``. Here the
checkpoint is *mapped* (the same philosophy as ``module_inject/hf.py``): a
diffusers safetensors/torch state dict loads into the
``SpatialUNet(diffusers_geometry=True)`` / ``SpatialVAEDecoder`` pytrees.

Layout conversions: torch conv ``[O, I, kh, kw]`` -> HWIO ``[kh, kw, I, O]``;
torch linear ``[O, I]`` -> ``[I, O]``; norm ``weight/bias`` -> ``scale/bias``.
diffusers' attention ``to_q/to_k/to_v`` carry no bias — imported as zeros
(numerically identical).

Every checkpoint key must be consumed (or match an explicit ignore pattern:
the VAE file also carries the encoder) and every model leaf must be filled —
a silent partial load would "work" and produce garbage samples.

Usage::

    cfg = SpatialConfig(base_channels=320, channel_mults=(1, 2, 4, 4),
                        n_res_blocks=2, n_heads=8, context_dim=768,
                        groups=32, diffusers_geometry=True)
    unet = DSUNet(SpatialUNet(cfg),
                  params=load_diffusers_unet("unet/", cfg))

``export_diffusers_unet`` / ``export_diffusers_vae_decoder`` are the exact
inverses (used by the round-trip tests; also lets edited weights save back).
"""

import os
import re

import numpy as np

import jax

from .spatial import SpatialConfig  # noqa: F401  (re-export convenience)


def _np(v):
    if hasattr(v, "detach"):  # torch tensor
        v = v.detach().cpu().numpy()
    return np.asarray(v)


def load_state_dict(path_or_state):
    """Accept a dict (torch/numpy values), a safetensors file, a torch .bin
    file, or a diffusers model directory containing either."""
    if isinstance(path_or_state, dict):
        return {k: _np(v) for k, v in path_or_state.items()}
    path = path_or_state
    if os.path.isdir(path):
        for name in ("diffusion_pytorch_model.safetensors",
                     "diffusion_pytorch_model.bin"):
            cand = os.path.join(path, name)
            if os.path.isfile(cand):
                path = cand
                break
        else:
            raise FileNotFoundError(
                f"no diffusers weights (diffusion_pytorch_model.*) in {path}")
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return dict(load_file(path))
    import torch

    return {k: _np(v) for k, v in
            torch.load(path, map_location="cpu", weights_only=True).items()}


class _Mapper:
    """Consumes checkpoint keys; tracks what was read so leftovers error."""

    def __init__(self, state):
        self.state = state
        self.used = set()

    def take(self, key):
        if key not in self.state:
            raise KeyError(f"diffusers checkpoint is missing {key!r} — wrong "
                           f"config geometry for this file?")
        self.used.add(key)
        return self.state[key]

    def conv(self, pre):
        return {"kernel": np.transpose(self.take(pre + ".weight"), (2, 3, 1, 0)),
                "bias": self.take(pre + ".bias")}

    def linear(self, pre, zeros_bias_dim=None):
        w = self.take(pre + ".weight").T
        if pre + ".bias" in self.state:
            b = self.take(pre + ".bias")
        else:  # diffusers to_q/to_k/to_v have no bias
            b = np.zeros((zeros_bias_dim if zeros_bias_dim is not None
                          else w.shape[1],), w.dtype)
        return {"kernel": w, "bias": b}

    def norm(self, pre):
        return {"scale": self.take(pre + ".weight"),
                "bias": self.take(pre + ".bias")}

    def resnet(self, pre, temb):
        p = {"norm1": self.norm(pre + ".norm1"),
             "conv1": self.conv(pre + ".conv1"),
             "norm2": self.norm(pre + ".norm2"),
             "conv2": self.conv(pre + ".conv2")}
        if temb:
            p["temb"] = self.linear(pre + ".time_emb_proj")
        if pre + ".conv_shortcut.weight" in self.state:
            p["skip"] = self.conv(pre + ".conv_shortcut")
        return p

    def attn_pair(self, pre):
        return {"q": self.linear(pre + ".to_q"),
                "k": self.linear(pre + ".to_k"),
                "v": self.linear(pre + ".to_v"),
                "o": self.linear(pre + ".to_out.0")}

    def transformer2d(self, pre):
        blocks = []
        d = 0
        while f"{pre}.transformer_blocks.{d}.norm1.weight" in self.state:
            tb = f"{pre}.transformer_blocks.{d}"
            blocks.append({
                "ln1": self.norm(tb + ".norm1"),
                "attn1": self.attn_pair(tb + ".attn1"),
                "ln2": self.norm(tb + ".norm2"),
                "attn2": self.attn_pair(tb + ".attn2"),
                "ln3": self.norm(tb + ".norm3"),
                "ff_proj": self.linear(tb + ".ff.net.0.proj"),
                "ff_out": self.linear(tb + ".ff.net.2"),
            })
            d += 1
        if not blocks:
            raise KeyError(f"no transformer_blocks under {pre}")
        return {"norm": self.norm(pre + ".norm"),
                "proj_in": self.conv(pre + ".proj_in"),
                "blocks": blocks,
                "proj_out": self.conv(pre + ".proj_out")}

    def finish(self, ignore=()):
        left = [k for k in self.state
                if k not in self.used
                and not any(re.match(pat, k) for pat in ignore)]
        if left:
            raise ValueError(
                f"{len(left)} unconsumed checkpoint keys (geometry mismatch?):"
                f" {sorted(left)[:12]}...")


def load_diffusers_unet(path_or_state, config):
    """diffusers UNet2DConditionModel state dict -> SpatialUNet
    (``diffusers_geometry=True``) values pytree."""
    if not config.diffusers_geometry:
        raise ValueError("load_diffusers_unet needs "
                         "SpatialConfig(diffusers_geometry=True)")
    m = _Mapper(load_state_dict(path_or_state))
    chans = [config.base_channels * mult for mult in config.channel_mults]
    p = {"conv_in": m.conv("conv_in"),
         "temb1": m.linear("time_embedding.linear_1"),
         "temb2": m.linear("time_embedding.linear_2")}
    down = []
    for i in range(len(chans)):
        blocks = []
        for j in range(config.n_res_blocks):
            blk = {"res": m.resnet(f"down_blocks.{i}.resnets.{j}", temb=True)}
            if config.attn_at(i):
                blk["attn"] = m.transformer2d(f"down_blocks.{i}.attentions.{j}")
            blocks.append(blk)
        ds = None
        if i < len(chans) - 1:
            ds = m.conv(f"down_blocks.{i}.downsamplers.0.conv")
        down.append({"blocks": blocks, "downsample": ds})
    p["down"] = down
    p["mid"] = {"res1": m.resnet("mid_block.resnets.0", temb=True),
                "attn": m.transformer2d("mid_block.attentions.0"),
                "res2": m.resnet("mid_block.resnets.1", temb=True)}
    up = []
    for k in range(len(chans)):
        level = len(chans) - 1 - k
        blocks = []
        for j in range(config.n_res_blocks + 1):
            blk = {"res": m.resnet(f"up_blocks.{k}.resnets.{j}", temb=True)}
            if config.attn_at(level):
                blk["attn"] = m.transformer2d(f"up_blocks.{k}.attentions.{j}")
            blocks.append(blk)
        us = None
        if k < len(chans) - 1:
            us = m.conv(f"up_blocks.{k}.upsamplers.0.conv")
        up.append({"blocks": blocks, "upsample": us})
    p["up"] = up
    p["norm_out"] = m.norm("conv_norm_out")
    p["conv_out"] = m.conv("conv_out")
    m.finish()
    return p


def load_diffusers_vae_decoder(path_or_state, config):
    """diffusers AutoencoderKL state dict (decoder half + post_quant_conv) ->
    SpatialVAEDecoder (``diffusers_geometry=True``) values pytree. Encoder and
    quant_conv keys in a full-VAE file are ignored."""
    if not config.diffusers_geometry:
        raise ValueError("load_diffusers_vae_decoder needs "
                         "SpatialConfig(diffusers_geometry=True)")
    m = _Mapper(load_state_dict(path_or_state))
    n_up = len(config.channel_mults)
    p = {"post_quant_conv": m.conv("post_quant_conv"),
         "conv_in": m.conv("decoder.conv_in"),
         "mid": {"res1": m.resnet("decoder.mid_block.resnets.0", temb=False),
                 "attn": {"group_norm": m.norm(
                              "decoder.mid_block.attentions.0.group_norm"),
                          **m.attn_pair("decoder.mid_block.attentions.0")},
                 "res2": m.resnet("decoder.mid_block.resnets.1", temb=False)},
         "up": []}
    for k in range(n_up):
        blocks = [m.resnet(f"decoder.up_blocks.{k}.resnets.{j}", temb=False)
                  for j in range(config.n_res_blocks + 1)]
        conv = None
        if k < n_up - 1:
            conv = m.conv(f"decoder.up_blocks.{k}.upsamplers.0.conv")
        p["up"].append({"blocks": blocks, "conv": conv})
    p["norm_out"] = m.norm("decoder.conv_norm_out")
    p["conv_out"] = m.conv("decoder.conv_out")
    m.finish(ignore=(r"encoder\.", r"quant_conv\."))
    return p


# ---------------------------------------------------------------------------------
# exporters (exact inverses; round-trip tested)
# ---------------------------------------------------------------------------------
def _ex_conv(out, pre, p):
    # ascontiguousarray: safetensors serializes the raw buffer, and a
    # transposed VIEW would silently save the un-transposed data
    out[pre + ".weight"] = np.ascontiguousarray(
        np.transpose(np.asarray(p["kernel"]), (3, 2, 0, 1)))
    out[pre + ".bias"] = np.asarray(p["bias"])


def _ex_lin(out, pre, p):
    out[pre + ".weight"] = np.ascontiguousarray(np.asarray(p["kernel"]).T)
    out[pre + ".bias"] = np.asarray(p["bias"])


def _ex_norm(out, pre, p):
    out[pre + ".weight"] = np.asarray(p["scale"])
    out[pre + ".bias"] = np.asarray(p["bias"])


def _ex_resnet(out, pre, p):
    _ex_norm(out, pre + ".norm1", p["norm1"])
    _ex_conv(out, pre + ".conv1", p["conv1"])
    _ex_norm(out, pre + ".norm2", p["norm2"])
    _ex_conv(out, pre + ".conv2", p["conv2"])
    if "temb" in p:
        _ex_lin(out, pre + ".time_emb_proj", p["temb"])
    if "skip" in p:
        _ex_conv(out, pre + ".conv_shortcut", p["skip"])


def _ex_attn_pair(out, pre, p):
    for ours, theirs in (("q", "to_q"), ("k", "to_k"), ("v", "to_v")):
        _ex_lin(out, f"{pre}.{theirs}", p[ours])
    _ex_lin(out, pre + ".to_out.0", p["o"])


def _ex_transformer2d(out, pre, p):
    _ex_norm(out, pre + ".norm", p["norm"])
    _ex_conv(out, pre + ".proj_in", p["proj_in"])
    for d, tb in enumerate(p["blocks"]):
        b = f"{pre}.transformer_blocks.{d}"
        _ex_norm(out, b + ".norm1", tb["ln1"])
        _ex_attn_pair(out, b + ".attn1", tb["attn1"])
        _ex_norm(out, b + ".norm2", tb["ln2"])
        _ex_attn_pair(out, b + ".attn2", tb["attn2"])
        _ex_norm(out, b + ".norm3", tb["ln3"])
        _ex_lin(out, b + ".ff.net.0.proj", tb["ff_proj"])
        _ex_lin(out, b + ".ff.net.2", tb["ff_out"])
    _ex_conv(out, pre + ".proj_out", p["proj_out"])


def export_diffusers_unet(params, config):
    out = {}
    _ex_conv(out, "conv_in", params["conv_in"])
    _ex_lin(out, "time_embedding.linear_1", params["temb1"])
    _ex_lin(out, "time_embedding.linear_2", params["temb2"])
    for i, stage in enumerate(params["down"]):
        for j, blk in enumerate(stage["blocks"]):
            _ex_resnet(out, f"down_blocks.{i}.resnets.{j}", blk["res"])
            if "attn" in blk:
                _ex_transformer2d(out, f"down_blocks.{i}.attentions.{j}",
                                  blk["attn"])
        if stage["downsample"] is not None:
            _ex_conv(out, f"down_blocks.{i}.downsamplers.0.conv",
                     stage["downsample"])
    _ex_resnet(out, "mid_block.resnets.0", params["mid"]["res1"])
    _ex_transformer2d(out, "mid_block.attentions.0", params["mid"]["attn"])
    _ex_resnet(out, "mid_block.resnets.1", params["mid"]["res2"])
    for k, stage in enumerate(params["up"]):
        for j, blk in enumerate(stage["blocks"]):
            _ex_resnet(out, f"up_blocks.{k}.resnets.{j}", blk["res"])
            if "attn" in blk:
                _ex_transformer2d(out, f"up_blocks.{k}.attentions.{j}",
                                  blk["attn"])
        if stage["upsample"] is not None:
            _ex_conv(out, f"up_blocks.{k}.upsamplers.0.conv", stage["upsample"])
    _ex_norm(out, "conv_norm_out", params["norm_out"])
    _ex_conv(out, "conv_out", params["conv_out"])
    return out


def export_diffusers_vae_decoder(params, config):
    out = {}
    _ex_conv(out, "post_quant_conv", params["post_quant_conv"])
    _ex_conv(out, "decoder.conv_in", params["conv_in"])
    _ex_resnet(out, "decoder.mid_block.resnets.0", params["mid"]["res1"])
    _ex_norm(out, "decoder.mid_block.attentions.0.group_norm",
             params["mid"]["attn"]["group_norm"])
    _ex_attn_pair(out, "decoder.mid_block.attentions.0", params["mid"]["attn"])
    _ex_resnet(out, "decoder.mid_block.resnets.1", params["mid"]["res2"])
    for k, stage in enumerate(params["up"]):
        for j, res in enumerate(stage["blocks"]):
            _ex_resnet(out, f"decoder.up_blocks.{k}.resnets.{j}", res)
        if stage["conv"] is not None:
            _ex_conv(out, f"decoder.up_blocks.{k}.upsamplers.0.conv",
                     stage["conv"])
    _ex_norm(out, "decoder.conv_norm_out", params["norm_out"])
    _ex_conv(out, "decoder.conv_out", params["conv_out"])
    return out
