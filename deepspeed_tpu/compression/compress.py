"""Compression: quantization-aware training + pruning over parameter pytrees.

Reference: ``deepspeed/compression/compress.py`` (``init_compression:95``,
``redundancy_clean:123``) walks ``nn.Module``s and swaps layers for
``LinearLayer_Compress`` (``basic_layer.py:121``) carrying quant/prune state.
TPU-native: parameters are pytrees, so compression is a *pytree transform* —
``init_compression`` returns a transform applied inside the training step
(fake-quant / masks are jittable), and ``redundancy_clean`` bakes the final
quantized/pruned values for deployment. Scheduling (progressive bit reduction,
offsets) follows the MoQ scheduler (``compression/scheduler.py``).
"""

import fnmatch

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.quantizer import fake_quantize, quantize, dequantize
from ..utils.logging import log_dist
from .config import CompressionConfig


def _matches(path_key, patterns):
    return any(fnmatch.fnmatch(path_key, pat) or pat == "*" for pat in patterns)


def _leaf_keys(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    keys = ["/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            for path, _ in flat]
    return keys, [l for _, l in flat], treedef


class CompressionScheduler:
    """MoQ-style progressive quantization schedule (reference
    ``compression/scheduler.py``): bits anneal from start_bits to target_bits
    every ``quantize_period`` steps after ``schedule_offset``."""

    def __init__(self, config: CompressionConfig):
        self.config = config

    def bits_at(self, step):
        wq = self.config.weight_quantization
        if not wq.enabled or step < wq.schedule_offset:
            return None  # no quantization yet
        periods = (step - wq.schedule_offset) // max(wq.quantize_period, 1)
        bits = max(wq.target_bits, wq.start_bits // (2 ** periods))
        return bits

    def prune_ratio_at(self, step):
        sp = self.config.sparse_pruning
        if not sp.enabled or step < sp.schedule_offset:
            return 0.0
        return sp.ratio


def init_compression(config, model_config=None) -> "CompressionScheduler":
    """Parse config -> scheduler + transform factory (reference ``compress.py:95``).

    Usage:
        scheduler = init_compression({"weight_quantization": {...}}, model_cfg)
        params_q = scheduler.compress_params(params, step)   # inside/before step

    ``model_config`` (a ``TransformerConfig``) is required for head pruning
    (head_dim) and for activation quantization via ``apply_to_model_config``.
    """
    if not isinstance(config, CompressionConfig):
        config = CompressionConfig.from_dict(dict(config or {}))
    return _CompressionRuntime(config, model_config)


def apply_to_model_config(model_config, config):
    """Wire activation quantization into a model config (the reference swaps
    layers for QuantAct-wrapped ones; here the model's block reads
    ``activation_quant_bits`` and fake-quantizes its residual branches)."""
    import dataclasses

    if not isinstance(config, CompressionConfig):
        config = CompressionConfig.from_dict(dict(config or {}))
    aq = config.activation_quantization
    if not aq.enabled:
        return model_config
    if aq.schedule_offset > 0:
        log_dist(
            "activation_quantization.schedule_offset is not supported: the "
            "quantizer is part of the compiled model, so it engages from "
            "step 0 (train the warmup phase with it disabled instead)",
            ranks=[0])
    return dataclasses.replace(model_config,
                               activation_quant_bits=aq.bits,
                               activation_quant_group=aq.group_size)


class _CompressionRuntime(CompressionScheduler):
    def __init__(self, config: CompressionConfig, model_config=None):
        super().__init__(config)
        self.model_config = model_config
        if (config.head_pruning.enabled and model_config is None):
            raise ValueError(
                "head_pruning needs init_compression(config, model_config=...) "
                "for the head layout (head_dim)")

    def compress_params(self, params, step):
        """Apply fake-quant + pruning masks for the current step (jittable)."""
        wq = self.config.weight_quantization
        sp = self.config.sparse_pruning
        hp = self.config.head_pruning
        rp = self.config.row_pruning
        bits = self.bits_at(step)
        ratio = self.prune_ratio_at(step)
        head_on = hp.enabled and step >= hp.schedule_offset
        row_on = rp.enabled and step >= rp.schedule_offset
        if bits is None and ratio == 0.0 and not head_on and not row_on:
            return params

        if head_on:
            params, _ = _transform_heads(params, self.model_config.head_dim,
                                         hp.ratio, hp.modules, shrink=False)
        if row_on:
            params, _ = _transform_rows(params, rp, shrink=False)

        keys, leaves, treedef = _leaf_keys(params)
        out = []
        for key, leaf in zip(keys, leaves):
            x = leaf
            if ratio > 0.0 and leaf.ndim >= 2 and _matches(key, sp.modules):
                x = _prune(x, sp.method, ratio)
            if bits is not None and bits < 16 and leaf.ndim >= 2 \
                    and _matches(key, wq.modules):
                x = fake_quantize(x, bits=bits, group_size=wq.quantize_groups)
            out.append(x)
        return jax.tree_util.tree_unflatten(treedef, out)


def _prune(x, method, ratio):
    """Sparse pruning (reference ``compression/basic_layer.py`` SparsePruning):
    ``l1`` zeroes the globally smallest-|w| fraction; ``topk`` keeps the top
    (1-ratio) fraction per output row (structured along the last axis)."""
    if method == "topk":
        # index-based mask: exactly k survivors per row even with tied magnitudes
        k = max(1, int(x.shape[-1] * (1.0 - ratio)))
        idx = jnp.argsort(jnp.abs(x), axis=-1)[..., -k:]
        mask = jnp.put_along_axis(jnp.zeros_like(x), idx, 1.0, axis=-1,
                                  inplace=False)
        return x * mask
    if method not in (None, "l1"):
        raise ValueError(f"unknown sparse_pruning method {method!r}; "
                         "expected 'l1' or 'topk'")
    flat = jnp.abs(x).reshape(-1)
    k = int(flat.shape[0] * ratio)
    if k == 0:
        return x
    threshold = jnp.sort(flat)[k - 1]
    mask = (jnp.abs(x) > threshold).astype(x.dtype)
    return x * mask


def _keep_count(n, ratio):
    return max(1, int(round(n * (1.0 - ratio))))


def _head_groups(keys, patterns):
    """Attention groups: prefixes g with ``g/o/kernel`` present (zoo naming)."""
    suffix = "/o/kernel"
    return [k[:-len(suffix)] for k in keys
            if k.endswith(suffix) and _matches(k[:-len(suffix)], patterns)]


def _gather_or_mask(x, idx, axis, n_groups, shrink):
    """Keep the ``idx`` groups along ``axis`` (gather when shrinking, zero-mask
    otherwise). ``x`` is reshaped so ``axis`` splits into (n_groups, per_group).

    ``idx`` is [lead..., K] where lead are x's leading dims (the stacked
    ``layers`` dim, or nothing for an unstacked tree); between lead and
    ``axis`` it broadcasts (e.g. over d_model for qkv kernel columns).
    """
    shape = list(x.shape)
    axis = axis % x.ndim
    per = shape[axis] // n_groups
    grouped = x.reshape(shape[:axis] + [n_groups, per] + shape[axis + 1:])
    lead = idx.ndim - 1
    K = idx.shape[-1]
    idx_shape = list(idx.shape[:lead]) + [1] * (grouped.ndim - lead)
    idx_shape[axis] = K
    expand = idx.reshape(idx_shape)
    if shrink:
        kept = jnp.take_along_axis(grouped, expand, axis=axis)
        out_shape = shape[:axis] + [K * per] + shape[axis + 1:]
        return kept.reshape(out_shape)
    mask_shape = [1] * grouped.ndim
    mask_shape[:lead] = list(idx.shape[:lead])
    mask_shape[axis] = n_groups
    mask = jnp.zeros(mask_shape, x.dtype)
    mask = jnp.put_along_axis(mask, expand, 1.0, axis=axis, inplace=False)
    return (grouped * mask).reshape(shape)


def _transform_heads(params, head_dim, ratio, patterns, shrink):
    """Head pruning (reference ``basic_layer.py:553``): score each attention
    head by the L1 mass of its output-projection rows; keep the top
    ``1 - ratio`` fraction. Returns (params, kept_heads_or_None)."""
    keys, leaves, treedef = _leaf_keys(params)
    index = {k: i for i, k in enumerate(keys)}
    kept = None
    for g in _head_groups(keys, patterns):
        o = leaves[index[g + "/o/kernel"]]
        H = o.shape[-2] // head_dim
        if H <= 1:
            continue
        scores = jnp.sum(
            jnp.abs(o).reshape(o.shape[:-2] + (H, head_dim, o.shape[-1])),
            axis=(-1, -2))
        K = _keep_count(H, ratio)
        kept = K
        idx = jnp.sort(jnp.argsort(scores, axis=-1)[..., -K:], axis=-1)
        for proj in ("q", "k", "v"):
            kk = f"{g}/{proj}/kernel"
            if kk not in index:
                continue
            if leaves[index[kk]].shape[-1] != H * head_dim:
                raise ValueError(
                    f"head_pruning requires MHA ({kk} width "
                    f"{leaves[index[kk]].shape[-1]} != {H}x{head_dim}); "
                    f"GQA/MQA layouts are not head-prunable")
            leaves[index[kk]] = _gather_or_mask(
                leaves[index[kk]], idx, axis=-1, n_groups=H, shrink=shrink)
            bk = f"{g}/{proj}/bias"
            if bk in index:
                leaves[index[bk]] = _gather_or_mask(
                    leaves[index[bk]], idx, axis=-1, n_groups=H, shrink=shrink)
        leaves[index[g + "/o/kernel"]] = _gather_or_mask(
            o, idx, axis=-2, n_groups=H, shrink=shrink)
    return jax.tree_util.tree_unflatten(treedef, leaves), kept


def _row_groups(keys, rp):
    """MLP groups as (prefix, producer_suffixes, consumer_suffix). The
    configured producer/consumer pair is matched first; with the default
    naming, SwiGLU triples (up+gate -> down) are recognized too, and a sibling
    ``gate`` is ALWAYS co-pruned with its producer — shrinking ``up`` without
    ``gate`` would crash silu(gate) * up at the first forward."""
    keyset = set(keys)
    pairs = [(rp.producer, rp.consumer)]
    if rp.producer == "fc":
        pairs.append(("up", "down"))
    groups = []
    for producer, consumer in pairs:
        suffix = f"/{producer}/kernel"
        for k in keys:
            if not k.endswith(suffix):
                continue
            g = k[:-len(suffix)]
            if f"{g}/{consumer}/kernel" not in keyset or not _matches(g, rp.modules):
                continue
            producers = [producer]
            if producer != "gate" and f"{g}/gate/kernel" in keyset:
                producers.append("gate")
            groups.append((g, producers, consumer))
    return groups


def _transform_rows(params, rp, shrink):
    """Row pruning (reference ``basic_layer.py:437``): score each intermediate
    neuron by the L1 mass of its producing columns + consuming row; keep the
    top ``1 - ratio`` fraction of producer output cols and the matching
    consumer input rows. Returns (params, kept_rows_or_None)."""
    keys, leaves, treedef = _leaf_keys(params)
    index = {k: i for i, k in enumerate(keys)}
    kept = None
    for g, producers, consumer in _row_groups(keys, rp):
        ck = f"{g}/{consumer}/kernel"
        proj = leaves[index[ck]]               # [..., FF, d_out]
        FF = proj.shape[-2]
        scores = jnp.sum(jnp.abs(proj), axis=-1)
        for p in producers:                     # [..., d_in, FF] each
            scores = scores + jnp.sum(
                jnp.abs(leaves[index[f"{g}/{p}/kernel"]]), axis=-2)
        K = _keep_count(FF, rp.ratio)
        kept = K
        idx = jnp.sort(jnp.argsort(scores, axis=-1)[..., -K:], axis=-1)
        for p in producers:
            pk = f"{g}/{p}/kernel"
            leaves[index[pk]] = _gather_or_mask(
                leaves[index[pk]], idx, axis=-1, n_groups=FF, shrink=shrink)
            bk = f"{g}/{p}/bias"
            if bk in index:
                leaves[index[bk]] = _gather_or_mask(
                    leaves[index[bk]], idx, axis=-1, n_groups=FF, shrink=shrink)
        leaves[index[ck]] = _gather_or_mask(proj, idx, axis=-2, n_groups=FF,
                                            shrink=shrink)
    return jax.tree_util.tree_unflatten(treedef, leaves), kept


def _reduce_layers(params, lr):
    """Depth reduction: slice the stacked ``layers`` dim of every leaf under
    ``lr.module_prefix`` down to the kept block indices."""
    keys, leaves, treedef = _leaf_keys(params)
    stacked = [i for i, k in enumerate(keys) if k.startswith(lr.module_prefix)]
    if not stacked:
        raise ValueError(
            f"layer_reduction: no parameters under prefix {lr.module_prefix!r} "
            f"(is the model built with scan_layers stacking?)")
    L = leaves[stacked[0]].shape[0]
    if lr.teacher_layer:
        idx = np.asarray(sorted(set(int(i) for i in lr.teacher_layer)))
        if idx[0] < 0 or idx[-1] >= L:
            raise ValueError(f"layer_reduction.teacher_layer out of range for "
                             f"{L} layers: {list(idx)}")
    else:
        keep = lr.keep_number_layer
        if not 0 < keep <= L:
            raise ValueError(f"layer_reduction.keep_number_layer must be in "
                             f"[1, {L}], got {keep}")
        idx = np.unique(np.linspace(0, L - 1, keep).round().astype(int))
    for i in stacked:
        leaves[i] = leaves[i][idx]
    return jax.tree_util.tree_unflatten(treedef, leaves), len(idx)


def redundancy_clean(params, config, model_config=None):
    """Bake final compressed values for deployment (reference ``compress.py:123``):
    structured pruning/depth reduction physically SHRINK the tree, then
    quantized params are packed to int.

    Returns ``(params, packed)``, or ``(params, packed, new_model_config)``
    when ``model_config`` is given (n_layers / n_heads / d_ff updated to the
    shrunk shapes — required for head pruning, which needs head_dim)."""
    import dataclasses

    if not isinstance(config, CompressionConfig):
        config = CompressionConfig.from_dict(dict(config or {}))
    updates = {}
    if config.layer_reduction.enabled:
        params, n_layers = _reduce_layers(params, config.layer_reduction)
        updates["n_layers"] = n_layers
    if config.head_pruning.enabled:
        if model_config is None:
            raise ValueError("head_pruning shrink needs redundancy_clean("
                             "..., model_config=...) for head_dim")
        if getattr(model_config, "position_embedding", None) == "alibi":
            # ALiBi slopes are a function of head index and TOTAL head count;
            # re-deriving them for the shrunk count silently changes every
            # kept head's slope vs what it was trained with
            raise ValueError("head_pruning does not support ALiBi models: "
                             "slopes would be silently re-assigned")
        params, n_heads = _transform_heads(
            params, model_config.head_dim, config.head_pruning.ratio,
            config.head_pruning.modules, shrink=True)
        if n_heads is not None:
            updates["n_heads"] = n_heads
            # heads keep their original width; d_model stays (residual width),
            # so the derived d_model // n_heads would be wrong
            updates["head_dim_override"] = model_config.head_dim
            if getattr(model_config, "n_kv_heads", None) is not None:
                # MHA spelled explicitly (the width check in _transform_heads
                # already rejected GQA): kv heads shrink with the heads
                updates["n_kv_heads"] = n_heads
    if config.row_pruning.enabled:
        params, d_ff = _transform_rows(params, config.row_pruning, shrink=True)
        if d_ff is not None:
            updates["d_ff"] = d_ff

    wq = config.weight_quantization
    keys, leaves, treedef = _leaf_keys(params)
    packed = {}
    out = []
    n_quant = 0
    for key, leaf in zip(keys, leaves):
        if wq.enabled and leaf.ndim >= 2 and _matches(key, wq.modules):
            q, scale, meta = quantize(leaf, bits=wq.target_bits,
                                      group_size=wq.quantize_groups)
            packed[key] = {"q": np.asarray(q), "scale": np.asarray(scale),
                           "meta": meta}
            out.append(dequantize(q, scale, meta).astype(leaf.dtype))
            n_quant += 1
        else:
            out.append(leaf)
    log_dist(f"redundancy_clean: quantized {n_quant}/{len(leaves)} tensors to "
             f"int{wq.target_bits}"
             + (f"; shrunk {updates}" if updates else ""), ranks=[0])
    cleaned = jax.tree_util.tree_unflatten(treedef, out)
    if model_config is None:
        return cleaned, packed
    new_cfg = dataclasses.replace(model_config, **updates) if updates \
        else model_config
    return cleaned, packed, new_cfg
