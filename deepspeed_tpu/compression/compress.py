"""Compression: quantization-aware training + pruning over parameter pytrees.

Reference: ``deepspeed/compression/compress.py`` (``init_compression:95``,
``redundancy_clean:123``) walks ``nn.Module``s and swaps layers for
``LinearLayer_Compress`` (``basic_layer.py:121``) carrying quant/prune state.
TPU-native: parameters are pytrees, so compression is a *pytree transform* —
``init_compression`` returns a transform applied inside the training step
(fake-quant / masks are jittable), and ``redundancy_clean`` bakes the final
quantized/pruned values for deployment. Scheduling (progressive bit reduction,
offsets) follows the MoQ scheduler (``compression/scheduler.py``).
"""

import fnmatch

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.quantizer import fake_quantize, quantize, dequantize
from ..utils.logging import log_dist
from .config import CompressionConfig


def _matches(path_key, patterns):
    return any(fnmatch.fnmatch(path_key, pat) or pat == "*" for pat in patterns)


def _leaf_keys(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    keys = ["/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            for path, _ in flat]
    return keys, [l for _, l in flat], treedef


class CompressionScheduler:
    """MoQ-style progressive quantization schedule (reference
    ``compression/scheduler.py``): bits anneal from start_bits to target_bits
    every ``quantize_period`` steps after ``schedule_offset``."""

    def __init__(self, config: CompressionConfig):
        self.config = config

    def bits_at(self, step):
        wq = self.config.weight_quantization
        if not wq.enabled or step < wq.schedule_offset:
            return None  # no quantization yet
        periods = (step - wq.schedule_offset) // max(wq.quantize_period, 1)
        bits = max(wq.target_bits, wq.start_bits // (2 ** periods))
        return bits

    def prune_ratio_at(self, step):
        sp = self.config.sparse_pruning
        if not sp.enabled or step < sp.schedule_offset:
            return 0.0
        return sp.ratio


def init_compression(config) -> "CompressionScheduler":
    """Parse config -> scheduler + transform factory (reference ``compress.py:95``).

    Usage:
        scheduler = init_compression({"weight_quantization": {...}})
        params_q = scheduler.compress_params(params, step)   # inside/before step
    """
    if not isinstance(config, CompressionConfig):
        config = CompressionConfig.from_dict(dict(config or {}))
    return _CompressionRuntime(config)


class _CompressionRuntime(CompressionScheduler):
    def compress_params(self, params, step):
        """Apply fake-quant + pruning masks for the current step (jittable)."""
        wq = self.config.weight_quantization
        sp = self.config.sparse_pruning
        bits = self.bits_at(step)
        ratio = self.prune_ratio_at(step)
        if bits is None and ratio == 0.0:
            return params

        keys, leaves, treedef = _leaf_keys(params)
        out = []
        for key, leaf in zip(keys, leaves):
            x = leaf
            if ratio > 0.0 and leaf.ndim >= 2 and _matches(key, sp.modules):
                x = _prune(x, sp.method, ratio)
            if bits is not None and bits < 16 and leaf.ndim >= 2 \
                    and _matches(key, wq.modules):
                x = fake_quantize(x, bits=bits, group_size=wq.quantize_groups)
            out.append(x)
        return jax.tree_util.tree_unflatten(treedef, out)


def _prune(x, method, ratio):
    """Sparse pruning (reference ``compression/basic_layer.py`` SparsePruning):
    ``l1`` zeroes the globally smallest-|w| fraction; ``topk`` keeps the top
    (1-ratio) fraction per output row (structured along the last axis)."""
    if method == "topk":
        # index-based mask: exactly k survivors per row even with tied magnitudes
        k = max(1, int(x.shape[-1] * (1.0 - ratio)))
        idx = jnp.argsort(jnp.abs(x), axis=-1)[..., -k:]
        mask = jnp.put_along_axis(jnp.zeros_like(x), idx, 1.0, axis=-1,
                                  inplace=False)
        return x * mask
    if method not in (None, "l1"):
        raise ValueError(f"unknown sparse_pruning method {method!r}; "
                         "expected 'l1' or 'topk'")
    flat = jnp.abs(x).reshape(-1)
    k = int(flat.shape[0] * ratio)
    if k == 0:
        return x
    threshold = jnp.sort(flat)[k - 1]
    mask = (jnp.abs(x) > threshold).astype(x.dtype)
    return x * mask


def redundancy_clean(params, config):
    """Bake final quantized values for deployment (reference ``compress.py:123``):
    returns (int8 leaves + scales) for quantized params, pruned values zeroed."""
    if not isinstance(config, CompressionConfig):
        config = CompressionConfig.from_dict(dict(config or {}))
    wq = config.weight_quantization
    keys, leaves, treedef = _leaf_keys(params)
    packed = {}
    out = []
    n_quant = 0
    for key, leaf in zip(keys, leaves):
        if wq.enabled and leaf.ndim >= 2 and _matches(key, wq.modules):
            q, scale, meta = quantize(leaf, bits=wq.target_bits,
                                      group_size=wq.quantize_groups)
            packed[key] = {"q": np.asarray(q), "scale": np.asarray(scale),
                           "meta": meta}
            out.append(dequantize(q, scale, meta).astype(leaf.dtype))
            n_quant += 1
        else:
            out.append(leaf)
    log_dist(f"redundancy_clean: quantized {n_quant}/{len(leaves)} tensors to "
             f"int{wq.target_bits}", ranks=[0])
    return jax.tree_util.tree_unflatten(treedef, out), packed
