from .compress import (init_compression, redundancy_clean,
                       apply_to_model_config, CompressionScheduler)
from .config import CompressionConfig

__all__ = ["init_compression", "redundancy_clean", "apply_to_model_config",
           "CompressionScheduler", "CompressionConfig"]
