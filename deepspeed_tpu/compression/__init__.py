from .compress import init_compression, redundancy_clean, CompressionScheduler
from .config import CompressionConfig

__all__ = ["init_compression", "redundancy_clean", "CompressionScheduler",
           "CompressionConfig"]
