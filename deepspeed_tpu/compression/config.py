"""Compression config (reference ``deepspeed/compression/config.py`` shape)."""

from ..config.base import ConfigModel


class WeightQuantizeConfig(ConfigModel):
    enabled: bool = False
    target_bits: int = 8
    start_bits: int = 16
    quantize_period: int = 100        # steps between bit reductions (MoQ schedule)
    quantize_groups: int = 64         # group size
    schedule_offset: int = 0          # step at which quantization starts
    modules: list = ["*"]             # glob patterns on param paths


class ActivationQuantizeConfig(ConfigModel):
    """Reference ``basic_layer.py:17`` QuantAct. On TPU this is a model-config
    knob (``TransformerConfig.activation_quant_bits``) wired by
    ``apply_to_model_config``: activations are fake-quantized in-graph on the
    attention/MLP residual branches (dynamic symmetric groupwise ranges; the
    reference's "static" running-range calibration maps to dynamic here — the
    range reduction happens per group inside the compiled step)."""

    enabled: bool = False
    bits: int = 8
    group_size: int = 64
    range_calibration: str = "dynamic"  # dynamic | static (treated as dynamic)
    schedule_offset: int = 0


class SparsePruningConfig(ConfigModel):
    enabled: bool = False
    method: str = "l1"                # l1 | topk
    ratio: float = 0.5
    schedule_offset: int = 0
    modules: list = ["*"]


class RowPruningConfig(ConfigModel):
    """Structured MLP-neuron pruning (reference ``basic_layer.py:437``): zero
    (then shrink) output columns of the producing linear and the matching input
    rows of the consuming linear. ``modules`` matches the producer group
    (zoo naming: ``blocks/mlp`` with ``fc`` producing and ``proj`` consuming);
    the reference's explicit ``related_modules`` pairing is the
    producer/consumer suffix pair here."""

    enabled: bool = False
    ratio: float = 0.5
    schedule_offset: int = 0
    modules: list = ["*"]
    producer: str = "fc"              # suffix of the producing linear
    consumer: str = "proj"            # suffix of the consuming linear


class HeadPruningConfig(ConfigModel):
    """Attention-head pruning (reference ``basic_layer.py:553``): heads scored
    by the L1 mass of their output-projection rows; lowest-``ratio`` fraction
    masked during training and physically removed by ``redundancy_clean``."""

    enabled: bool = False
    ratio: float = 0.5
    schedule_offset: int = 0
    modules: list = ["*"]


class LayerReductionConfig(ConfigModel):
    """Depth reduction (reference ``compression/config.py`` layer_reduction):
    keep a subset of transformer blocks. With scan-stacked layers this is a
    slice of the leading ``layers`` dim. ``teacher_layer`` lists the block
    indices to keep; otherwise ``keep_number_layer`` evenly-spaced blocks."""

    enabled: bool = False
    keep_number_layer: int = 0
    teacher_layer: list = []
    module_prefix: str = "blocks"     # stacked-subtree prefix in the param tree


class CompressionConfig(ConfigModel):
    weight_quantization: WeightQuantizeConfig = WeightQuantizeConfig
    activation_quantization: ActivationQuantizeConfig = ActivationQuantizeConfig
    sparse_pruning: SparsePruningConfig = SparsePruningConfig
    row_pruning: RowPruningConfig = RowPruningConfig
    head_pruning: HeadPruningConfig = HeadPruningConfig
    layer_reduction: LayerReductionConfig = LayerReductionConfig
