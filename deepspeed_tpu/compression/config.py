"""Compression config (reference ``deepspeed/compression/config.py`` shape)."""

from ..config.base import ConfigModel


class WeightQuantizeConfig(ConfigModel):
    enabled: bool = False
    target_bits: int = 8
    start_bits: int = 16
    quantize_period: int = 100        # steps between bit reductions (MoQ schedule)
    quantize_groups: int = 64         # group size
    schedule_offset: int = 0          # step at which quantization starts
    modules: list = ["*"]             # glob patterns on param paths


class ActivationQuantizeConfig(ConfigModel):
    enabled: bool = False
    bits: int = 8
    range_calibration: str = "dynamic"  # dynamic | static
    schedule_offset: int = 0


class SparsePruningConfig(ConfigModel):
    enabled: bool = False
    method: str = "l1"                # l1 | topk
    ratio: float = 0.5
    schedule_offset: int = 0
    modules: list = ["*"]


class RowPruningConfig(ConfigModel):
    enabled: bool = False
    ratio: float = 0.5
    schedule_offset: int = 0
    modules: list = ["*"]


class CompressionConfig(ConfigModel):
    weight_quantization: WeightQuantizeConfig = WeightQuantizeConfig
    activation_quantization: ActivationQuantizeConfig = ActivationQuantizeConfig
    sparse_pruning: SparsePruningConfig = SparsePruningConfig
    row_pruning: RowPruningConfig = RowPruningConfig
