"""Unified step-trace telemetry.

``SpanTracer`` records nested host spans (optionally device-fenced with
``block_until_ready``) against a pluggable clock and emits Chrome-trace
JSON (Perfetto-loadable) plus structured JSONL. Wired into the training
engine's step phases, the serving engine's request lifecycles, and
checkpoint save/resume; analyzed by ``tools/trace_summary.py``.
"""

from .analysis import (counters_by_step, load_jsonl, phase_table,
                       request_metrics)
from .digest import LatencyDigest, evaluate_slo
from .fleet import (build_wide_events, digest_from_wide_events,
                    fleet_chrome_trace, latency_rollup, load_wide_events,
                    merge_fleet_events, slowest_requests, write_fleet_trace)
from .health import (HEALTH_STAT_KEYS, HealthHalted, HealthMonitor,
                     batch_fingerprint, derive_group_names,
                     group_health_stats, load_dump, record_from_stats,
                     replay_records)
from .tracer import SpanTracer

__all__ = [
    "SpanTracer",
    "LatencyDigest",
    "evaluate_slo",
    "merge_fleet_events",
    "fleet_chrome_trace",
    "build_wide_events",
    "digest_from_wide_events",
    "load_wide_events",
    "latency_rollup",
    "slowest_requests",
    "write_fleet_trace",
    "load_jsonl",
    "request_metrics",
    "phase_table",
    "counters_by_step",
    "HEALTH_STAT_KEYS",
    "HealthHalted",
    "HealthMonitor",
    "batch_fingerprint",
    "derive_group_names",
    "group_health_stats",
    "load_dump",
    "record_from_stats",
    "replay_records",
]
