"""Deterministic mergeable latency digests for streaming SLO percentiles.

``LatencyDigest`` is a fixed-geometry log-bucket histogram: O(1) memory
(one int array whose geometry never depends on the data), deterministic
insertion (a value always lands in the same bucket), and EXACT merge
associativity (merging is integer bucket-count addition, so
``(a+b)+c == a+(b+c) == digest(all samples)`` bucket for bucket). That is
what lets per-replica digests roll up into one fleet digest whose
percentiles are independent of merge order or replica count — the property
sample-list percentiles and most sketches (t-digest, GK) do not have.

The quantile a digest reports is the UPPER EDGE of the nearest-rank bucket
— a canonical representative, so any two digests holding the same samples
report bit-identical percentiles no matter how the samples were sharded.
Resolution is the bucket growth factor (~7.8% relative); the tier-1
coherence pins compare digest-to-digest (exact), never digest-to-raw.

The same arithmetic must read the live metrics AND the merged trace (the
PR 4 trace==metrics discipline), so it lives here in telemetry/ and is
imported by both ``serving/metrics.py`` and ``tools/fleet_report.py``.
"""

import math

# one fixed geometry for every digest in the process: merges across
# replicas/tools are only defined between identical geometries, and a
# config knob here would quietly break cross-artifact comparability
DIGEST_LO = 1e-6          # values at/below this land in bucket 0
DIGEST_N_BUCKETS = 360    # 12 decades at ~7.8% relative resolution
DIGEST_GROWTH = 10.0 ** (12.0 / DIGEST_N_BUCKETS)
_LOG_GROWTH = math.log(DIGEST_GROWTH)


class LatencyDigest:
    """Fixed-bucket log histogram with exact merge.

    Values are clock units (seconds under a wall clock, virtual units under
    a ``VirtualClock``); ``quantile_ms`` applies the x1e3 display convention
    the serving metrics use.
    """

    __slots__ = ("counts", "count")

    def __init__(self):
        self.counts = [0] * DIGEST_N_BUCKETS
        self.count = 0

    @staticmethod
    def bucket_index(value):
        """The bucket a value lands in — the single canonical mapping every
        producer and consumer shares."""
        v = float(value)
        if v <= DIGEST_LO:
            return 0
        i = int(math.floor(math.log(v / DIGEST_LO) / _LOG_GROWTH))
        return min(max(i, 0), DIGEST_N_BUCKETS - 1)

    @staticmethod
    def bucket_upper(index):
        """Canonical representative of a bucket: its upper edge."""
        return DIGEST_LO * DIGEST_GROWTH ** (index + 1)

    def add(self, value):
        self.counts[self.bucket_index(value)] += 1
        self.count += 1

    def remove(self, value):
        """Retract one previously-added sample (the unhealthy-shed TTFT
        retraction path). A value never added decrements nothing."""
        i = self.bucket_index(value)
        if self.counts[i] > 0:
            self.counts[i] -= 1
            self.count -= 1

    def merge(self, other):
        """In-place exact merge (integer bucket addition)."""
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        return self

    @classmethod
    def merged(cls, digests):
        out = cls()
        for d in digests:
            out.merge(d)
        return out

    def quantile_bucket(self, q):
        """Bucket index of the nearest-rank quantile; None when empty."""
        if self.count == 0:
            return None
        rank = max(1, int(math.ceil(q / 100.0 * self.count)))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return i
        return DIGEST_N_BUCKETS - 1

    def quantile(self, q):
        """Nearest-rank quantile (q in [0, 100]) as the bucket upper edge;
        None when empty. Deterministic: equal bucket counts -> equal
        quantiles, regardless of how the samples were sharded or merged."""
        i = self.quantile_bucket(q)
        return None if i is None else self.bucket_upper(i)

    def quantile_ms(self, q):
        v = self.quantile(q)
        return None if v is None else v * 1e3

    def count_above(self, value):
        """Samples in buckets strictly above ``value``'s bucket (bucket
        resolution: same-bucket samples count as NOT above)."""
        i = self.bucket_index(value)
        return sum(self.counts[i + 1:])

    # ------------------------------------------------------------ snapshots
    def snapshot(self):
        """Sparse machine-readable form (the artifact/fleet.json block).
        Geometry is recorded so a reader can refuse a foreign digest."""
        return {
            "lo": DIGEST_LO,
            "growth": DIGEST_GROWTH,
            "n_buckets": DIGEST_N_BUCKETS,
            "count": self.count,
            "buckets": {str(i): c for i, c in enumerate(self.counts) if c},
        }

    @classmethod
    def from_snapshot(cls, snap):
        if (int(snap.get("n_buckets", -1)) != DIGEST_N_BUCKETS
                or abs(float(snap.get("lo", 0.0)) - DIGEST_LO) > 0.0
                or abs(float(snap.get("growth", 0.0)) - DIGEST_GROWTH)
                > 1e-12):
            raise ValueError("digest geometry mismatch: snapshot was not "
                             "produced by this digest version")
        d = cls()
        for i, c in snap.get("buckets", {}).items():
            d.counts[int(i)] = int(c)
        d.count = int(snap.get("count", sum(d.counts)))
        return d

    def percentiles_ms(self, qs=(50, 90, 99)):
        return {f"p{q}": self.quantile_ms(q) for q in qs}


def evaluate_slo(targets_ms, digests):
    """Grade latency digests against ``serving.slo`` targets.

    ``targets_ms``: {"ttft_p99_ms": t1, "tpot_p99_ms": t2, ...} — 0/None
    disables a target. ``digests``: {"ttft": LatencyDigest, ...} keyed by
    the metric prefix of each target. Returns the machine-readable ``slo``
    block shared by ServingMetrics events, the Router snapshot, the bench
    artifact and ``tools/fleet_report.py``:

    - ``observed_p99_ms`` per metric (digest quantile, the SAME number the
      ``Serving/<metric>_p99_ms`` monitor event carries);
    - per-metric ``violated`` (observed > target) and ``burn_rate`` — the
      fraction of samples over the target divided by the 1% error budget a
      P99 objective grants (burn_rate 1.0 = burning budget exactly as fast
      as allowed; >1 = out of budget at steady state);
    - ``pass``: no configured target violated.
    """
    out = {"configured": False, "pass": True, "targets_ms": {},
           "observed_p99_ms": {}, "violated": {}, "burn_rate": {}}
    for key, target in (targets_ms or {}).items():
        if not key.endswith("_p99_ms"):
            continue
        metric = key[:-len("_p99_ms")]
        d = digests.get(metric)
        observed = d.quantile_ms(99) if d is not None else None
        out["observed_p99_ms"][metric] = observed
        if not target or target <= 0:
            continue
        out["configured"] = True
        out["targets_ms"][metric] = float(target)
        # violation is judged at BUCKET granularity: the reported quantile
        # is a bucket's upper edge, so comparing it raw against the target
        # would flag a fleet whose every sample is under target purely from
        # the ~7.8% quantization (observed edge > target, burn rate 0.0 —
        # self-contradictory). P99's bucket must sit strictly above the
        # target's bucket, the same resolution count_above/burn_rate use.
        p99_bucket = d.quantile_bucket(99) if d is not None else None
        violated = (p99_bucket is not None
                    and p99_bucket
                    > LatencyDigest.bucket_index(float(target) / 1e3))
        out["violated"][metric] = violated
        frac_over = (d.count_above(float(target) / 1e3) / d.count
                     if d is not None and d.count else 0.0)
        out["burn_rate"][metric] = round(frac_over / 0.01, 4)
        if violated:
            out["pass"] = False
    return out
