"""Numerics flight recorder: in-graph health stats + host-side watchdog.

The third observability axis next to the span tracer (*time*, PR 4) and the
program sanitizer (*program shape*, PR 5): **numerical health**. The fp16
stack treats numerics as a binary overflow flag (``ops/loss_scaler.py``
``check_overflow`` -> skip step); a run that merely *drifts* — a loss spike,
one param group's grad norm exploding, quantization drift from the int8
gather wire — is invisible until it is dead, and when it dies nothing is
captured for post-mortem. This module closes both gaps:

- :func:`group_health_stats` — per-parameter-group grad-norm, param-norm,
  update-norm, max-abs and nonfinite counts, computed **inside** the jitted
  train step as one small extra side output (a handful of ``[G]``-shaped
  f32 vectors; no host callbacks, so the sanitizer's ``transfer`` rule and
  the donation budgets stay green). Groups are derived from the param
  pytree by :func:`derive_group_names` (embeddings / per-layer block
  components / norms / head).

- :class:`HealthMonitor` — a host-side ring buffer of the last N step
  records (health stats + loss, loss_scale, skipped flag, rng key, batch
  fingerprint) with pluggable detectors (nonfinite counts, z-score
  loss/grad-norm spike over a trailing window, update/param-ratio ceiling)
  and a configurable action per detector: ``warn | skip_step | dump |
  halt``. ``skip_step`` is realized *in-graph* (the engine extends the
  fp16 overflow-skip to any-dtype nonfinite grads); window-based detectors
  cannot retroactively skip an applied update, so for them ``skip_step``
  degrades to ``warn``.

- **black-box dumps** — on detector fire, on SIGTERM (hooked through
  ``ElasticAgent``), and on unhandled ``train_batch`` exceptions, the ring
  buffer + provenance stamp is published through the
  ``checkpoint/atomic.py`` commit protocol (stage -> fsync -> CRC marker
  -> rename), so a crash cannot strand a half-written dump. The marker
  ``kind="health_dump"`` keeps dumps out of the checkpoint resume chain.
  ``tools/health_report.py`` renders the timeline and replays detectors.
"""

import collections
import json
import os
import sys
import time

from ..utils.logging import logger

#: The in-graph side output: one f32 vector of length ``n_groups`` per key.
#: Keys are fixed so compiled-program out_shardings stay stable whether or
#: not the host-side monitor is enabled.
HEALTH_STAT_KEYS = (
    "grad_norm",        # per-group L2 norm of the unscaled (pre-clip) grads
    "grad_max_abs",     # per-group max |g|
    "grad_nonfinite",   # per-group count of non-finite grad elements
    "param_norm",       # per-group L2 norm of the (old) fp32 masters
    "update_norm",      # per-group L2 norm of (new_params - params)
    "param_nonfinite",  # per-group count of non-finite NEW param elements
)

ACTIONS = ("off", "warn", "skip_step", "dump", "halt")


class HealthHalted(RuntimeError):
    """Raised by the engine when a detector with ``action="halt"`` fires
    (after the black-box dump is published)."""


# ---------------------------------------------------------------------------
# param grouping (derived from the pytree, not configured)
# ---------------------------------------------------------------------------
def _path_keys(path):
    out = []
    for k in path:
        if hasattr(k, "key"):
            out.append(str(k.key))
        elif hasattr(k, "idx"):
            out.append(str(k.idx))
        elif hasattr(k, "name"):
            out.append(str(k.name))
        else:
            out.append(str(k))
    return out


def classify_param_path(path):
    """Map one param-leaf path to its health group.

    The vocabulary mirrors how numerics actually fail: embeddings drift
    differently from attention blocks, norms are tiny-but-critical, the
    head sees the loss first. Stacked ``blocks`` split by component
    (``blocks/attn``, ``blocks/mlp``, ...) — norms anywhere group as
    ``norms``.
    """
    keys = [k.lower() for k in _path_keys(path)]
    if any(k.startswith("ln") or "norm" in k for k in keys):
        return "norms"
    if any("head" in k for k in keys):
        return "head"
    if any("emb" in k or k in ("wte", "wpe") for k in keys):
        return "embeddings"
    if keys and keys[0] == "blocks":
        return f"blocks/{keys[1]}" if len(keys) > 1 else "blocks"
    return "other"


def derive_group_names(tree, is_leaf=None):
    """Stable, first-appearance-ordered group names for a param(-shaped)
    pytree. The same function classifies leaves at trace time inside
    :func:`group_health_stats`, so index order always agrees."""
    import jax

    paths, _ = jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)
    names = []
    for path, _leaf in paths:
        g = classify_param_path(path)
        if g not in names:
            names.append(g)
    return tuple(names)


# ---------------------------------------------------------------------------
# in-graph stats (traced into the jitted step — no host callbacks)
# ---------------------------------------------------------------------------
def group_health_stats(grads, params, new_params, group_names):
    """Per-group health statistics as ``{key: f32[G]}`` (see
    :data:`HEALTH_STAT_KEYS`). Pure jnp — safe inside jit; the group
    membership is resolved at trace time from the grads pytree's paths.

    ``grads`` must be the *unscaled* gradients (the engine computes these
    before clipping); ``params``/``new_params`` are the step's old and new
    parameter trees (update_norm prices the applied update — zero on a
    skipped step).
    """
    import jax
    import jax.numpy as jnp

    from ..ops.loss_scaler import count_nonfinite

    names = list(group_names)
    idx = {n: i for i, n in enumerate(names)}
    G = len(names)
    zero = jnp.zeros((), jnp.float32)
    gsq = [zero] * G
    gmax = [zero] * G
    gnf = [zero] * G
    psq = [zero] * G
    usq = [zero] * G
    pnf = [zero] * G

    g_paths, _ = jax.tree_util.tree_flatten_with_path(grads)
    p_leaves = jax.tree_util.tree_leaves(params)
    n_leaves = jax.tree_util.tree_leaves(new_params)
    assert len(g_paths) == len(p_leaves) == len(n_leaves), \
        "grads/params/new_params trees disagree"
    for (path, g), p, n in zip(g_paths, p_leaves, n_leaves):
        i = idx[classify_param_path(path)]
        g32 = g.astype(jnp.float32)
        gsq[i] = gsq[i] + jnp.sum(g32 * g32)
        gmax[i] = jnp.maximum(gmax[i], jnp.max(jnp.abs(g32)))
        gnf[i] = gnf[i] + count_nonfinite(g)
        p32 = p.astype(jnp.float32)
        psq[i] = psq[i] + jnp.sum(p32 * p32)
        d = n.astype(jnp.float32) - p32
        usq[i] = usq[i] + jnp.sum(d * d)
        pnf[i] = pnf[i] + count_nonfinite(n)
    return {
        "grad_norm": jnp.sqrt(jnp.stack(gsq)),
        "grad_max_abs": jnp.stack(gmax),
        "grad_nonfinite": jnp.stack(gnf),
        "param_norm": jnp.sqrt(jnp.stack(psq)),
        "update_norm": jnp.sqrt(jnp.stack(usq)),
        "param_nonfinite": jnp.stack(pnf),
    }


def batch_fingerprint(batch):
    """Cheap deterministic fingerprint of a host batch (CRC over leaf
    bytes, key-sorted) — pins *which data* fed the step that went bad.
    Accepts one micro-batch dict or a sequence of them (a gas>1 window:
    every micro is chained into one CRC, so two windows differing in ANY
    micro fingerprint differently)."""
    import zlib

    import numpy as np

    if batch is None:
        return None
    micros = batch if isinstance(batch, (list, tuple)) else [batch]
    h = 0
    try:
        for mb in micros:
            for k in sorted(mb):
                h = zlib.crc32(k.encode(), h)
                h = zlib.crc32(
                    np.ascontiguousarray(np.asarray(mb[k])).tobytes(), h)
    except Exception:
        return None
    return f"{h & 0xFFFFFFFF:08x}"


def record_from_stats(step, group_names, stats, loss=None, loss_scale=1.0,
                      skipped=False, grad_norm=None, lr=None, rng=None,
                      fingerprint=None, extra=None):
    """Build the host-side JSON-able step record from the device stats
    (this is the one host sync the health path pays per observed step)."""
    import numpy as np

    host = {k: np.asarray(v, dtype=np.float64) for k, v in stats.items()}
    groups = {}
    for i, name in enumerate(group_names):
        pn = float(host["param_norm"][i])
        un = float(host["update_norm"][i])
        groups[name] = {
            "grad_norm": float(host["grad_norm"][i]),
            "grad_max_abs": float(host["grad_max_abs"][i]),
            "grad_nonfinite": float(host["grad_nonfinite"][i]),
            "param_norm": pn,
            "update_norm": un,
            "update_ratio": (un / pn) if pn > 0 else 0.0,
            "param_nonfinite": float(host["param_nonfinite"][i]),
        }
    rec = {
        "step": int(step),
        "time": time.time(),
        "loss": None if loss is None else float(loss),
        "loss_scale": float(loss_scale),
        "skipped": bool(skipped),
        "grad_norm": None if grad_norm is None else float(grad_norm),
        "lr": None if lr is None else float(lr),
        "rng": None if rng is None else [int(x) for x in rng],
        "batch_fingerprint": fingerprint,
        "groups": groups,
    }
    if extra:
        rec.update(extra)
    return rec


# ---------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------
class Anomaly:
    __slots__ = ("detector", "action", "step", "message", "groups")

    def __init__(self, detector, action, step, message, groups=()):
        self.detector = detector
        self.action = action
        self.step = step
        self.message = message
        self.groups = list(groups)

    def to_dict(self):
        return {"detector": self.detector, "action": self.action,
                "step": self.step, "message": self.message,
                "groups": self.groups}


class NonfiniteDetector:
    """Fires when any group reports non-finite grad or (new-)param
    elements, naming the offending group(s) with their counts."""

    name = "nonfinite"

    def __init__(self, action):
        self.action = action

    def check(self, record, history):
        bad = []
        for g, s in record.get("groups", {}).items():
            n = s.get("grad_nonfinite", 0.0) + s.get("param_nonfinite", 0.0)
            if n and n == n:  # NaN counts can't happen; guard anyway
                bad.append((g, n))
        if not bad:
            return None
        bad.sort(key=lambda x: -x[1])
        msg = ", ".join(f"{g} ({int(n)} elems)" for g, n in bad)
        return Anomaly(self.name, self.action, record["step"],
                       f"non-finite values in param group(s): {msg}",
                       groups=[g for g, _ in bad])


class SpikeDetector:
    """Z-score spike on a scalar record field (``loss`` or ``grad_norm``)
    over a trailing window. The std floor (2% of |mean|) keeps a flat
    trailing window from firing on benign jitter."""

    def __init__(self, metric, action, zscore=6.0, window=32, min_steps=8):
        self.metric = metric
        self.name = f"{metric}_spike"
        self.action = action
        self.zscore = float(zscore)
        # clamp: window=0 would slice the FULL history, min_steps=0 would
        # divide by zero on an empty prior (CLI overrides bypass config
        # validation, so the detector defends itself)
        self.window = max(1, int(window))
        self.min_steps = max(1, int(min_steps))

    def check(self, record, history):
        x = record.get(self.metric)
        if x is None or x != x:  # NaN is the nonfinite detector's job
            return None
        prior = [r[self.metric] for r in history
                 if r.get(self.metric) is not None
                 and r[self.metric] == r[self.metric]][-self.window:]
        if len(prior) < self.min_steps:
            return None
        mean = sum(prior) / len(prior)
        var = sum((v - mean) ** 2 for v in prior) / len(prior)
        std = max(var ** 0.5, 0.02 * abs(mean), 1e-12)
        z = (x - mean) / std
        if z <= self.zscore:
            return None
        return Anomaly(self.name, self.action, record["step"],
                       f"{self.metric} spike: {x:.6g} is {z:.1f} sigma above "
                       f"the trailing-{len(prior)} mean {mean:.6g}")


class UpdateRatioDetector:
    """Fires when any group's update/param ratio exceeds the ceiling — the
    classic sign of a step about to blow up (lr too high for that group,
    or a poisoned grad that is still finite)."""

    name = "update_ratio"

    def __init__(self, action, ceiling):
        self.action = action
        self.ceiling = float(ceiling)

    def check(self, record, history):
        bad = [(g, s.get("update_ratio", 0.0))
               for g, s in record.get("groups", {}).items()
               if s.get("update_ratio", 0.0) > self.ceiling]
        if not bad:
            return None
        bad.sort(key=lambda x: -x[1])
        msg = ", ".join(f"{g} ({r:.3g})" for g, r in bad)
        return Anomaly(self.name, self.action, record["step"],
                       f"update/param ratio above {self.ceiling:g}: {msg}",
                       groups=[g for g, _ in bad])


def build_detectors(cfg):
    """Detector set from a ``health`` config block (or any object with the
    same fields). Window-based detectors degrade ``skip_step`` to ``warn``:
    by the time a trailing-window statistic fires, the update is applied
    and the old params are donated away — only the in-graph nonfinite skip
    can act *before* the update lands."""
    dets = []
    if cfg.nonfinite_action != "off":
        dets.append(NonfiniteDetector(cfg.nonfinite_action))
    spike_action = cfg.spike_action
    if spike_action == "skip_step":
        logger.warning(
            "health: spike_action=skip_step cannot retroactively skip an "
            "applied update (trailing-window detector); degrading to warn")
        spike_action = "warn"
    if spike_action != "off" and cfg.spike_zscore > 0:
        dets.append(SpikeDetector("loss", spike_action, cfg.spike_zscore,
                                  cfg.spike_window, cfg.spike_min_steps))
        dets.append(SpikeDetector("grad_norm", spike_action, cfg.spike_zscore,
                                  cfg.spike_window, cfg.spike_min_steps))
    ur_action = cfg.update_ratio_action
    if ur_action == "skip_step":
        logger.warning("health: update_ratio_action=skip_step is post-update "
                       "by construction; degrading to warn")
        ur_action = "warn"
    if cfg.update_ratio_max > 0 and ur_action != "off":
        dets.append(UpdateRatioDetector(ur_action, cfg.update_ratio_max))
    return dets


def replay_records(records, cfg):
    """Re-run the detector set over a saved trajectory (the
    ``health_report`` CLI path and its planted/clean self-test). Actions
    are not executed — this returns the anomalies a live monitor with this
    config would have fired."""
    dets = build_detectors(cfg)
    history = []
    fired = []
    for rec in records:
        for d in dets:
            a = d.check(rec, history)
            if a is not None:
                fired.append(a)
        history.append(rec)
    return fired


# ---------------------------------------------------------------------------
# the host-side monitor
# ---------------------------------------------------------------------------
def _provenance(config=None):
    """The ``tools/_common.py`` run stamp (git SHA + config hash + backend),
    used verbatim so dumps carry the same provenance as bench artifacts.
    Degrades to a minimal stamp outside a repo checkout."""
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "tools")
    try:
        if tools not in sys.path:
            sys.path.insert(0, tools)
        from _common import run_stamp

        return run_stamp(config)
    except Exception:
        return {"git_sha": "unknown",
                "stamp_time": time.strftime("%Y-%m-%dT%H:%M:%S%z")}


class HealthMonitor:
    """Ring buffer + detectors + black-box dump for one engine.

    ``observe(record)`` runs every detector against the record and the
    trailing history, executes each fired detector's action (``warn`` logs;
    ``dump``/``halt`` publish the ring buffer atomically; ``skip_step`` is
    the engine's in-graph job and logs here), emits ``Health/*`` scalar
    events through the monitor fan-out, and returns the fired anomalies.
    The caller decides whether a ``halt`` anomaly raises (the engine does).
    """

    def __init__(self, config, group_names, monitor=None, meta=None):
        self.cfg = config
        self.enabled = bool(config is not None
                            and getattr(config, "enabled", False))
        self.group_names = tuple(group_names)
        self.monitor = monitor
        self.meta = dict(meta or {})
        self.records = collections.deque(
            maxlen=int(getattr(config, "window", 256) or 256))
        self.detectors = build_detectors(config) if self.enabled else []
        self.anomalies = []
        self.steps_observed = 0
        self.last_step = 0
        self._dump_count = 0
        self._dump_cap_warned = False

    @property
    def anomaly_count(self):
        return len(self.anomalies)

    # -- checkpoint carry ---------------------------------------------------
    def state_dict(self):
        """The ring-buffer window as a JSON-able dict — checkpointed by the
        engine so a resumed run's spike/z-score detectors see the SAME
        trailing history the uninterrupted run would have (a blind window
        after every preemption would mute the detectors for ``spike_window``
        steps each restart)."""
        return {
            "records": list(self.records),
            "steps_observed": self.steps_observed,
            "last_step": self.last_step,
            "anomalies": [a.to_dict() for a in self.anomalies],
        }

    def load_state_dict(self, state):
        self.records.clear()
        self.records.extend(state.get("records", ()))
        self.steps_observed = int(state.get("steps_observed", 0))
        self.last_step = int(state.get("last_step", 0))
        self.anomalies = [
            Anomaly(d.get("detector", "?"), d.get("action", "warn"),
                    d.get("step", 0), d.get("message", ""),
                    tuple(d.get("groups", ())))
            for d in state.get("anomalies", ())]

    def snapshot(self):
        """Machine-readable rollup (bench provenance rides this)."""
        return {
            "enabled": self.enabled,
            "steps_observed": self.steps_observed,
            "anomaly_count": self.anomaly_count,
            "anomalies_by_detector": dict(collections.Counter(
                a.detector for a in self.anomalies)),
            "dumps_published": self._dump_count,
            "last_step": self.last_step,
        }

    # -- the per-step path --------------------------------------------------
    def observe(self, record):
        if not self.enabled:
            return []
        history = list(self.records)
        fired = []
        for det in self.detectors:
            a = det.check(record, history)
            if a is not None:
                fired.append(a)
        record = dict(record, anomalies=[a.detector for a in fired])
        self.records.append(record)
        self.steps_observed += 1
        self.last_step = record["step"]
        for a in fired:
            self.anomalies.append(a)
            logger.warning("health[%s/%s] step %d: %s", a.detector, a.action,
                           a.step, a.message)
            if a.action in ("dump", "halt"):
                self.dump(a.detector, extra={"anomaly": a.to_dict()})
        self._emit_events(record)
        return fired

    def _emit_events(self, record):
        if self.monitor is None or not getattr(self.monitor, "enabled", False) \
                or not getattr(self.cfg, "emit_events", True):
            return
        step = record["step"]
        groups = record.get("groups", {})
        nonfinite = sum(s.get("grad_nonfinite", 0.0)
                        + s.get("param_nonfinite", 0.0)
                        for s in groups.values())
        ur_max = max((s.get("update_ratio", 0.0) for s in groups.values()),
                     default=0.0)
        events = [
            ("Health/grad_norm", record.get("grad_norm") or 0.0, step),
            ("Health/loss_scale", record.get("loss_scale", 1.0), step),
            ("Health/nonfinite", nonfinite, step),
            ("Health/update_ratio_max", ur_max, step),
            ("Health/anomalies", float(self.anomaly_count), step),
        ]
        if record.get("loss") is not None:
            events.append(("Health/loss", record["loss"], step))
        self.monitor.write_events(events)

    # -- the black box ------------------------------------------------------
    def dump(self, reason, extra=None):
        """Publish the ring buffer as an atomically-committed dump dir.
        Never raises — the flight recorder must not take down the flight.
        Returns the published path (or None)."""
        try:
            return self._dump(reason, extra)
        except Exception as e:
            logger.warning("health: black-box dump (%s) failed: %s",
                           reason, e)
            return None

    def _dump(self, reason, extra=None):
        from .. import comm as dist
        from ..checkpoint import atomic

        if dist.get_rank() != 0:
            return None
        max_dumps = int(getattr(self.cfg, "max_dumps", 8) or 8)
        if self._dump_count >= max_dumps:
            if not self._dump_cap_warned:
                self._dump_cap_warned = True
                logger.warning(
                    "health: dump cap reached (max_dumps=%d); suppressing "
                    "further black-box dumps this run", max_dumps)
            return None
        base = getattr(self.cfg, "dump_dir", "") or "./health_dumps"
        os.makedirs(base, exist_ok=True)
        tag = f"health-step{self.last_step}-{reason}"
        n = 0
        while os.path.exists(os.path.join(base, tag)):
            n += 1
            tag = f"health-step{self.last_step}-{reason}.{n}"
        path = os.path.join(base, tag)
        stage = atomic.make_stage_dir(path)
        blob = ("".join(json.dumps(r) + "\n" for r in self.records)).encode()
        crcs = {"records.jsonl": atomic.write_bytes(
            os.path.join(stage, "records.jsonl"), blob)}
        meta = {
            "reason": reason,
            "step": self.last_step,
            "group_names": list(self.group_names),
            "meta": self.meta,
            "anomalies": [a.to_dict() for a in self.anomalies[-100:]],
            "config": self._config_dict(),
            "extra": extra or {},
            "provenance": _provenance(self._config_dict()),
        }
        crcs["meta.json"] = atomic.write_json(
            os.path.join(stage, "meta.json"), meta)
        atomic.write_marker(stage, tag, meta={"step": self.last_step},
                            file_crcs=crcs, kind="health_dump")
        atomic.publish_tag(path)
        self._dump_count += 1
        logger.warning("health: black-box dump published: %s (%d records)",
                       path, len(self.records))
        return path

    def _config_dict(self):
        to_dict = getattr(self.cfg, "to_dict", None)
        if callable(to_dict):
            try:
                return to_dict()
            except Exception:
                pass
        return {k: getattr(self.cfg, k) for k in (
            "enabled", "window", "check_interval", "nonfinite_action",
            "spike_zscore", "spike_window", "spike_min_steps", "spike_action",
            "update_ratio_max", "update_ratio_action", "max_dumps")
            if hasattr(self.cfg, k)}


# ---------------------------------------------------------------------------
# dump loading (shared with tools/health_report.py)
# ---------------------------------------------------------------------------
def load_dump(path, verify=True):
    """Load a black-box dump dir (or a bare records JSONL file). Returns
    ``(records, meta, verify_result)`` where ``verify_result`` is the
    ``(ok, reason)`` pair from the atomic marker check (``(True, "jsonl")``
    for bare files)."""
    from ..checkpoint import atomic

    if os.path.isfile(path):
        records = _read_jsonl(path)
        return records, {}, (True, "jsonl")
    ok, reason = (True, "not verified")
    if verify:
        ok, reason = atomic.verify_checkpoint_dir(path)
    try:
        records = _read_jsonl(os.path.join(path, "records.jsonl"))
    except (OSError, ValueError):
        if ok:  # marker said good but the records don't parse: surface it
            raise
        records = []  # torn dump: the verdict is the verify failure
    meta = {}
    meta_path = os.path.join(path, "meta.json")
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            if ok:
                raise
    return records, meta, (ok, reason)


def _read_jsonl(path):
    from .analysis import load_jsonl

    return load_jsonl(path)
