"""Trace-event analysis: per-request serving metrics and per-step phase
tables, computed from the structured JSONL a ``SpanTracer`` emits.

Shared by ``tools/trace_summary.py`` (the CLI) and the tier-1 tests that
assert trace-derived TTFT/TPOT matches ``ServingMetrics`` — the same
arithmetic must read both, so it lives here rather than in either.
"""

import collections
import json


def load_jsonl(path):
    """Read one trace JSONL file -> list of event dicts (blank lines ok)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


def request_metrics(events):
    """Per-request TTFT/TPOT from serving lifecycle events.

    Reads the events ``serving/engine.py`` emits: ``request/queued``
    (args: request_id, start — arrival or submit time), ``request/
    first_token`` and ``request/finish`` (args: request_id, n_tokens).
    TTFT = first_token.ts - queued.start (queueing delay counts, same
    contract as ``Request.ttft``); TPOT = (finish.ts - first_token.ts) /
    (n_tokens - 1), None under 2 tokens — same contract as ``Request.tpot``.
    """
    out = {}
    for e in events:
        if not e.get("name", "").startswith("request/"):
            continue
        rid = e.get("args", {}).get("request_id")
        if rid is None:
            continue
        r = out.setdefault(rid, {"ttft": None, "tpot": None, "n_tokens": None,
                                 "finish_reason": None, "shed_reason": None})
        kind = e["name"].split("/", 1)[1]
        if kind == "queued":
            r["_start"] = e["args"].get("start", e["ts"])
        elif kind == "first_token":
            r["_first"] = e["ts"]
        elif kind == "finish":
            r["_finish"] = e["ts"]
            r["n_tokens"] = e["args"].get("n_tokens")
            r["finish_reason"] = e["args"].get("reason")
        elif kind == "shed":
            r["shed_reason"] = e["args"].get("reason")
    for r in out.values():
        first, start = r.pop("_first", None), r.pop("_start", None)
        finish = r.pop("_finish", None)
        if first is not None and start is not None:
            r["ttft"] = first - start
        if finish is not None and first is not None \
                and (r["n_tokens"] or 0) >= 2:
            r["tpot"] = (finish - first) / (r["n_tokens"] - 1)
    return out


def phase_table(events, step_key="step"):
    """Per-step phase durations from span events carrying a ``step`` arg.

    Returns ``(steps, phases)`` where ``steps`` is an ordered dict
    ``{step: {phase: seconds}}`` (durations of same-named spans within a
    step sum — micro-steps fold into their phase) and ``phases`` is the
    ordered list of phase names seen.
    """
    steps = collections.OrderedDict()
    phases = []
    for e in events:
        if e.get("ph") != "X":
            continue
        step = e.get("args", {}).get(step_key)
        if step is None:
            continue
        row = steps.setdefault(step, collections.OrderedDict())
        name = e["name"]
        row[name] = row.get(name, 0.0) + e["dur"]
        if name not in phases:
            phases.append(name)
    return steps, phases


def counters_by_step(events, name):
    """Latest value of counter/scalar events named ``name`` per step.

    Accepts both tracer counter events (``ph == "C"`` with a ``step`` arg)
    and ``TraceFileMonitor`` scalar rows (``{"name", "value", "step"}``)."""
    out = {}
    for e in events:
        if e.get("name") != name:
            continue
        if e.get("ph") == "C":
            step = e.get("args", {}).get("step")
            value = e.get("args", {}).get("value")
        else:
            step, value = e.get("step"), e.get("value")
        if step is not None and value is not None:
            out[step] = float(value)
    return out
