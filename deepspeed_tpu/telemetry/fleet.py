"""Fleet trace merging: N per-replica span streams -> one trace + wide events.

The Router runs N ServingEngine replicas, each tracing its own request
lifecycle against its own (virtual or wall) clock, plus the router's own
``route/decision`` stream. This module aligns them into:

- **fleet trace.json** — one Chrome-trace file with one *process* row per
  source (router + each replica), loadable in Perfetto: the cross-replica
  request journey reads left to right on one shared timeline. Under virtual
  clocks the per-replica streams are already on one timeline (the router's
  discrete-event loop aligns their zero and steps the laggard), so merging
  is a sort, not a re-clocking.
- **merged spans.jsonl** — every event from every source, tagged with its
  ``replica`` label, time-ordered (the ``tools/trace_summary.py`` fleet
  input).
- **requests.jsonl** — one postmortem-grade WIDE EVENT per request that
  entered the fleet: the routing decision (score breakdown, affinity,
  rebalance), lifecycle timing (queue-wait/TTFT/TPOT and the
  queue/prefill/decode/preemption breakdown), chunk count, preemptions and
  replay tokens, KV-block high-water — everything "where did this
  request's latency go" needs, in one JSON object.

Wide-event TTFT/TPOT carry the exact contracts of ``Request.ttft``/
``.tpot`` (PR 4 pins trace == metrics under the virtual clock), so a
``LatencyDigest`` rebuilt from requests.jsonl is bucket-identical to the
live fleet digest — the tier-1 trace == digest == monitor-event pin.
"""

import json
import os

from .digest import LatencyDigest
from .tracer import event_to_chrome

# request lifecycle + routing instants the wide-event builder consumes
_LIFECYCLE = ("route/decision", "route/shed", "route/failover",
              "route/retry", "route/handoff", "route/rebalance",
              "request/queued", "request/shed",
              "request/first_token", "request/preempted",
              "request/priority_evicted",
              "request/resumed", "request/migrated_out", "request/migrated",
              "request/handoff_out", "request/handoff_in",
              "request/unhealthy", "request/finish")


def merge_fleet_events(sources):
    """``sources``: list of ``(label, events)`` (a SpanTracer's in-memory
    event dicts, or events loaded from its spans.jsonl). Returns one
    time-ordered stream, each event copied and tagged ``replica=<label>``
    (ties broken by source order then per-source sequence, so the merge is
    deterministic)."""
    merged = []
    for si, (label, events) in enumerate(sources):
        for e in events:
            ev = dict(e)
            ev["replica"] = label
            merged.append((float(e.get("ts", 0.0)), si,
                           int(e.get("seq", 0)), ev))
    merged.sort(key=lambda t: t[:3])
    return [m[3] for m in merged]


def fleet_chrome_trace(sources, meta=None):
    """Chrome Trace Event Format over every source: pid = source index,
    process_name = the source label (Perfetto shows one lane per replica)."""
    out = []
    for pid, (label, events) in enumerate(sources):
        out.append({"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                    "args": {"name": str(label)}})
        out.extend(event_to_chrome(e, pid=pid) for e in events)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": dict(meta or {}, merged_sources=[
                str(label) for label, _ in sources])}


def build_wide_events(merged_events):
    """Per-request wide events from a merged fleet stream.

    Returns ``{request_id: wide_event_dict}``. Timing fields are in clock
    units (multiply by 1e3 for the ms display convention); goodput fields
    (replay/padding/prefix-saved tokens, chunks, kv_blocks_peak) come
    verbatim from the engine's ``request/finish`` args — the merger
    reconstructs the journey, never re-derives engine counters."""
    reqs = {}

    def rec(rid):
        return reqs.setdefault(rid, {
            "request_id": rid, "trace_id": None, "state": None,
            "replica": None, "routing": None, "shed_reason": None,
            "tenant_id": None, "tenant_class": None,
            "priority_evictions": 0,
            "finish_reason": None, "prompt_len": None, "n_tokens": None,
            "chunks": 0, "preemptions": 0, "replay_tokens": 0,
            "padding_tokens": 0, "prefix_saved_tokens": 0,
            "kv_blocks_peak": 0, "drafted_tokens": 0,
            "accepted_tokens": 0, "rolled_back_tokens": 0,
            "migrations": 0, "failovers": 0, "retries": 0,
            "migrated_saved_tokens": 0,
            "handoffs": 0, "rebalances": 0,
            "queue_wait": None, "admit_wait": None,
            "ttft": None,
            "tpot": None, "breakdown": None,
            "_start": None, "_first": None, "_finish": None,
            "_prefill_dur": 0.0, "_prefill_ts": [],
            "_preempt_ts": [], "_resume_ts": [],
            "_migrate_out_ts": [], "_migrate_in_ts": [],
            "_handoff_out_ts": [], "_handoff_in_ts": [],
        })

    for e in merged_events:
        args = e.get("args", {})
        rid = args.get("request_id")
        if rid is None:
            continue
        name = e.get("name", "")
        if e.get("ph") == "X":
            if name in ("prefill", "prefill_chunk"):
                r = rec(rid)
                r["_prefill_ts"].append(e["ts"])
                # resume-replay chunks run INSIDE the preempted->resumed
                # stall window: their time is already attributed to
                # "preempted", and counting it here too would break the
                # breakdown's partition of finish - start
                if not args.get("resume"):
                    r["_prefill_dur"] += e.get("dur", 0.0)
            continue
        if name not in _LIFECYCLE:
            continue
        r = rec(rid)
        if args.get("trace_id") is not None:
            r["trace_id"] = args["trace_id"]
        if name == "route/decision":
            r["routing"] = {k: args.get(k) for k in
                            ("replica", "scores", "affinity", "rebalanced",
                             "policy")}
        elif name in ("route/shed", "request/shed"):
            r["state"] = "shed"
            r["shed_reason"] = args.get("reason")
        elif name == "request/queued":
            r["_start"] = args.get("start", e["ts"])
            r["prompt_len"] = args.get("prompt_len")
            r["replica"] = e.get("replica")
            if args.get("tenant_id") is not None:
                r["tenant_id"] = args["tenant_id"]
                r["tenant_class"] = args.get("tenant_class")
        elif name == "request/first_token":
            r["_first"] = e["ts"]
        elif name == "request/preempted":
            r["_preempt_ts"].append(e["ts"])
        elif name == "request/priority_evicted":
            # annotation only: the eviction's stall window is tracked by
            # its paired request/preempted instant
            r["priority_evictions"] += 1
        elif name == "request/resumed":
            r["_resume_ts"].append(e["ts"])
        elif name == "request/migrated_out":
            r["_migrate_out_ts"].append(e["ts"])
        elif name == "request/migrated":
            r["_migrate_in_ts"].append(e["ts"])
            r["migrations"] += 1
            r["migrated_saved_tokens"] += args.get("saved_tokens") or 0
            r["replica"] = e.get("replica", r["replica"])
        elif name == "request/handoff_out":
            r["_handoff_out_ts"].append(e["ts"])
        elif name == "request/handoff_in":
            r["_handoff_in_ts"].append(e["ts"])
            r["handoffs"] += 1
            r["migrated_saved_tokens"] += args.get("saved_tokens") or 0
            r["replica"] = e.get("replica", r["replica"])
        elif name == "route/rebalance":
            r["rebalances"] += 1
        elif name == "route/failover":
            r["failovers"] += 1
        elif name == "route/retry":
            r["retries"] += 1
        elif name == "request/finish":
            r["state"] = "finished"
            r["_finish"] = e["ts"]
            r["replica"] = e.get("replica", r["replica"])
            for k in ("finish_reason", "n_tokens", "prompt_len",
                      "queue_wait", "admit_wait", "chunks", "preemptions",
                      "replay_tokens", "padding_tokens",
                      "prefix_saved_tokens", "kv_blocks_peak",
                      "drafted_tokens", "accepted_tokens",
                      "rolled_back_tokens", "migrations", "failovers",
                      "retries", "handoffs", "rebalances",
                      "tenant_id", "tenant_class", "priority_evictions"):
                src = "reason" if k == "finish_reason" else k
                if args.get(src) is not None:
                    r[k] = args[src]

    for r in reqs.values():
        start, first = r.pop("_start"), r.pop("_first")
        finish = r.pop("_finish")
        prefill_ts = r.pop("_prefill_ts")
        prefill_dur = r.pop("_prefill_dur")
        pre, res = r.pop("_preempt_ts"), r.pop("_resume_ts")
        mo, mi = r.pop("_migrate_out_ts"), r.pop("_migrate_in_ts")
        ho, hi = r.pop("_handoff_out_ts"), r.pop("_handoff_in_ts")
        if first is not None and start is not None:
            r["ttft"] = first - start
        if finish is not None and first is not None \
                and (r["n_tokens"] or 0) >= 2:
            r["tpot"] = (finish - first) / (r["n_tokens"] - 1)
        if r["queue_wait"] is None and prefill_ts and start is not None:
            r["queue_wait"] = min(prefill_ts) - start
        # preemption stall: preempted -> resumed windows (the resume replay
        # prefill runs inside the window; an unresumed tail is open-ended
        # and attributed up to finish)
        stall = sum(b - a for a, b in zip(pre, res))
        if len(pre) > len(res) and finish is not None:
            stall += finish - pre[len(res)]
        # cross-replica move stall: migrated_out -> migrated windows,
        # attributed like a preemption stall. The two instants come from
        # different replicas' clocks, which can disagree mid-run under the
        # DES, so each window is clamped at zero.
        mstall = sum(max(b - a, 0.0) for a, b in zip(mo, mi))
        if len(mo) > len(mi) and finish is not None:
            mstall += max(finish - mo[len(mi)], 0.0)
        # disaggregated first-token handoff: prefill-side handoff_out ->
        # decode-side handoff_in (splice) windows, clamped like migration
        # stalls; a handoff that degraded to replay-resume on the decode
        # side has no handoff_in and clamps to the finish tail
        hstall = sum(max(b - a, 0.0) for a, b in zip(ho, hi))
        if len(ho) > len(hi) and finish is not None:
            hstall += max(finish - ho[len(hi)], 0.0)
        r["start"], r["finish"] = start, finish
        if finish is not None and start is not None:
            r["breakdown"] = {
                "queue_wait": r["queue_wait"] or 0.0,
                "prefill": prefill_dur,
                "preempted": stall,
                "migrated": mstall,
                "handoff": hstall,
                # elapsed decode attribution (co-batched wall share):
                # first token -> finish, minus preemption/migration/handoff
                # stalls
                "decode": max((finish - (first if first is not None
                                         else start))
                              - stall - mstall - hstall, 0.0),
            }
    return reqs


def load_wide_events(path):
    """Wide events from a fleet dir's ``requests.jsonl`` (or a bare file)
    -> ``{request_id: wide_event}``. The one parser every consumer
    (``tools/fleet_report.py``, ``tools/trace_summary.py``, tests) shares."""
    if os.path.isdir(path):
        path = os.path.join(path, "requests.jsonl")
    wide = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                r = json.loads(line)
                wide[r["request_id"]] = r
    return wide


def digest_from_wide_events(wide_events, field="ttft"):
    """Rebuild a ``LatencyDigest`` from wide events, under the SAME
    partition the live metrics enforce (unhealthy sheds' latencies are
    poison and were retracted live; router/queue sheds never had one)."""
    d = LatencyDigest()
    for r in wide_events.values():
        if r.get("finish_reason") == "unhealthy_slot":
            continue
        v = r.get(field)
        if v is not None:
            d.add(v)
    return d


def latency_rollup(wide_events):
    """Aggregate latency attribution over finished requests (clock units):
    where the fleet's time went — queue wait vs prefill vs decode vs
    preemption stalls. Shared by fleet_report and trace_summary so both
    CLIs attribute identically."""
    rollup = {k: 0.0 for k in ("queue_wait", "prefill", "decode",
                               "preempted", "migrated", "handoff")}
    for r in wide_events.values():
        if r.get("state") != "finished":
            continue
        for k, v in (r.get("breakdown") or {}).items():
            rollup[k] = rollup.get(k, 0.0) + v
    return rollup


def slowest_requests(wide_events, top_k=5):
    """Top-K slowest requests by TTFT, enriched for critical-path display
    (ms fields, dominant breakdown component, routing decision, goodput
    counters) — the one shape both CLIs render."""
    rows = sorted((r for r in wide_events.values()
                   if r.get("ttft") is not None),
                  key=lambda r: -r["ttft"])[:top_k]
    out = []
    for r in rows:
        b = r.get("breakdown") or {}
        total = None
        if r.get("finish") is not None and r.get("start") is not None:
            total = (r["finish"] - r["start"]) * 1e3
        out.append({
            "request_id": r["request_id"], "trace_id": r.get("trace_id"),
            "replica": r.get("replica"), "routing": r.get("routing"),
            "ttft_ms": r["ttft"] * 1e3, "total_ms": total,
            "breakdown_ms": {k: v * 1e3 for k, v in b.items()},
            "dominant": max(b, key=b.get) if b else None,
            "preemptions": r.get("preemptions") or 0,
            "replay_tokens": r.get("replay_tokens") or 0,
            "chunks": r.get("chunks") or 0,
            "kv_blocks_peak": r.get("kv_blocks_peak") or 0,
            "migrations": r.get("migrations") or 0,
            "failovers": r.get("failovers") or 0,
            "handoffs": r.get("handoffs") or 0,
        })
    return out


def write_fleet_trace(output_dir, sources, fleet=None):
    """Write the merged fleet dir: ``trace.json`` (Chrome/Perfetto),
    ``spans.jsonl`` (merged + replica-tagged), ``requests.jsonl`` (wide
    events, one line per request), ``fleet.json`` (the live rollup the
    caller passes — Router.snapshot(): router block, per-replica metrics,
    fleet percentiles/slo/goodput/digests). Returns a small manifest."""
    os.makedirs(output_dir, exist_ok=True)
    merged = merge_fleet_events(sources)
    with open(os.path.join(output_dir, "trace.json"), "w") as f:
        json.dump(fleet_chrome_trace(
            sources, meta={"process": "fleet"}), f)
    with open(os.path.join(output_dir, "spans.jsonl"), "w") as f:
        for e in merged:
            f.write(json.dumps(e) + "\n")
    wide = build_wide_events(merged)
    with open(os.path.join(output_dir, "requests.jsonl"), "w") as f:
        for rid in sorted(wide):
            f.write(json.dumps(wide[rid]) + "\n")
    if fleet is not None:
        with open(os.path.join(output_dir, "fleet.json"), "w") as f:
            json.dump(fleet, f, indent=1, default=str)
    return {"output_dir": output_dir, "events": len(merged),
            "requests": len(wide),
            "sources": [str(label) for label, _ in sources]}
