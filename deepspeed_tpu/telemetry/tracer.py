"""Span-based step tracer: nested host spans with optional device fencing.

The observability substrate for the three performance-critical loops (train
step, ZeRO-3 gather schedule, serving decode). A ``SpanTracer`` records
nested host-side spans (begin/end pairs) and instant events against a
pluggable clock, and emits two views of the same record:

- **Chrome-trace JSON** (``trace.json``): the Trace Event Format both
  ``chrome://tracing`` and Perfetto load directly — complete "X" events
  with microsecond ``ts``/``dur``, one row per thread;
- **structured JSONL** (``spans.jsonl``): one JSON object per finished
  span/instant, machine-readable for ``tools/trace_summary.py`` and the
  tier-1 TTFT/TPOT-from-trace assertions.

Host timers measure *dispatch* unless fenced: under jax's async dispatch a
``stop()`` right after a jitted call returns before the device has done any
work. A span opened with ``sync=True`` runs the tracer's ``sync_fn`` (or
``jax.block_until_ready`` on a value the body registered via
``sp.fence(x)``) before reading the end timestamp, so the span covers
execution, not enqueue. The serving tracer instead runs against the
scheduler's own clock (wall or virtual), which is what makes trace-derived
TTFT/TPOT bit-identical to ``ServingMetrics`` under the virtual clock.

The tracer is deliberately cheap when disabled (one attribute check, a
shared null span) so it can stay in the hot loops unconditionally.
"""

import json
import os
import threading
import time

from ..utils.logging import logger


class _NullSpan:
    """Reusable no-op span for disabled tracers."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def fence(self, value):
        pass

    def set(self, **args):
        pass


_NULL_SPAN = _NullSpan()


def event_to_chrome(e, pid=0):
    """One internal event dict -> Trace Event Format (seconds -> us).
    Shared by ``SpanTracer.to_chrome_trace`` and the fleet merger
    (``telemetry/fleet.py``), which assigns one pid per source so N
    replica streams render as N process lanes."""
    ev = {"ph": e["ph"], "name": e["name"], "cat": e.get("cat", ""),
          "ts": e["ts"] * 1e6, "pid": pid, "tid": e.get("tid", 0),
          "args": e.get("args", {})}
    if e["ph"] == "X":
        ev["dur"] = e.get("dur", 0.0) * 1e6
    elif e["ph"] == "i":
        ev["s"] = "t"
    elif e["ph"] == "C":
        ev["args"] = {e["name"]: e.get("args", {}).get("value", 0.0)}
    return ev


class _Span:
    __slots__ = ("tracer", "name", "cat", "sync", "args", "t0", "_fence")

    def __init__(self, tracer, name, cat, sync, args):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.sync = sync
        self.args = args
        self.t0 = None
        self._fence = None

    def fence(self, value):
        """Register device value(s) to ``block_until_ready`` at span end
        (only consulted when the span was opened with ``sync=True``)."""
        self._fence = value

    def set(self, **args):
        """Attach/override args after the span is open (e.g. a result
        computed inside the body)."""
        self.args.update(args)

    def __enter__(self):
        self.t0 = self.tracer._now()
        self.tracer._stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        tracer = self.tracer
        synced = False
        if self.sync and exc_type is None:
            synced = tracer._run_fence(self._fence)
        t1 = tracer._now()
        stack = tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        parent = stack[-1].name if stack else None
        args = self.args
        if synced:
            args = dict(args, synced=True)
        tracer._record({
            "ph": "X", "name": self.name, "cat": self.cat,
            "ts": self.t0, "dur": t1 - self.t0,
            "depth": len(stack), "parent": parent, "args": args,
        })
        return False


class SpanTracer:
    """Nested span recorder with Chrome-trace / JSONL emission.

    ``clock``: a zero-arg callable returning seconds (defaults to
    ``time.perf_counter``; the serving engine passes its scheduler clock so
    virtual-time runs trace in virtual time). ``sync_fn``: zero-arg device
    fence used by ``sync=True`` spans that registered no explicit value.
    """

    def __init__(self, enabled=True, clock=None, sync_fn=None,
                 max_events=100_000, output_path="", job_name="",
                 chrome_trace=True, jsonl=True, meta=None):
        self.enabled = bool(enabled)
        self._clock = clock or time.perf_counter
        self._sync_fn = sync_fn
        self.max_events = int(max_events)
        self.chrome_trace = chrome_trace
        self.jsonl = jsonl
        self.meta = dict(meta or {})
        self.events = []
        self.dropped = 0
        self._seq = 0
        self._local = threading.local()
        self._tids = {}
        self._jsonl_flushed = 0
        self._chrome_flushed = -1
        self.output_dir = None
        if output_path:
            self.output_dir = os.path.join(output_path, job_name) \
                if job_name else output_path

    @classmethod
    def from_config(cls, cfg, clock=None, sync_fn=None, meta=None):
        """Build from a ``telemetry`` config block (None/disabled -> a
        null tracer whose spans cost one attribute check). Multi-process
        runs write per-rank trace dirs (``<job_name>-rank<N>`` past rank
        0): a shared ``trace.json`` is whole-file rewritten and a shared
        ``spans.jsonl`` is truncated by each process's first flush, so
        same-path writers would clobber each other."""
        if cfg is None or not getattr(cfg, "enabled", False):
            return cls(enabled=False)
        job = cfg.job_name
        try:
            from .. import comm as dist

            rank = dist.get_rank()
        except Exception:
            rank = 0
        if rank > 0:
            job = f"{job}-rank{rank}"
        return cls(enabled=True, clock=clock, sync_fn=sync_fn,
                   max_events=cfg.max_events,
                   output_path=cfg.output_path or "./traces",
                   job_name=job,
                   chrome_trace=cfg.chrome_trace, jsonl=cfg.jsonl,
                   meta=meta)

    # ------------------------------------------------------------ internals
    def _now(self):
        return self._clock()

    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _tid(self):
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = self._tids[ident] = len(self._tids)
        return tid

    def _run_fence(self, value):
        try:
            if value is not None:
                import jax

                jax.block_until_ready(value)
                return True
            if self._sync_fn is not None:
                self._sync_fn()
                return True
        except Exception as e:  # tracing must never take down the step
            logger.warning("telemetry: device fence failed: %s", e)
        return False

    def _record(self, event):
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        event["tid"] = self._tid()
        event["seq"] = self._seq
        self._seq += 1
        self.events.append(event)

    # ------------------------------------------------------------------ API
    def span(self, name, cat="host", sync=False, **args):
        """Context manager recording one complete span. ``sync=True`` fences
        the device (``sp.fence(x)`` value, else the tracer's ``sync_fn``)
        before the end timestamp."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, sync, args)

    def instant(self, name, cat="mark", ts=None, **args):
        """Point event at ``ts`` (defaults to now)."""
        if not self.enabled:
            return
        self._record({
            "ph": "i", "name": name, "cat": cat,
            "ts": self._now() if ts is None else ts, "dur": 0.0,
            "depth": len(self._stack()), "parent": None, "args": args,
        })

    def counter(self, name, value, ts=None, **args):
        """Counter sample (rendered as a track in Perfetto)."""
        if not self.enabled:
            return
        self._record({
            "ph": "C", "name": name, "cat": "counter",
            "ts": self._now() if ts is None else ts, "dur": 0.0,
            "depth": 0, "parent": None,
            "args": dict(args, value=float(value)),
        })

    # ------------------------------------------------------------- emission
    def to_chrome_trace(self):
        """The Trace Event Format dict Perfetto/chrome://tracing load."""
        out = [{"ph": "M", "pid": 0, "tid": 0, "name": "process_name",
                "args": {"name": self.meta.get("process", "deepspeed_tpu")}}]
        out.extend(event_to_chrome(e) for e in self.events)
        return {"traceEvents": out, "displayTimeUnit": "ms",
                "otherData": dict(self.meta, dropped_events=self.dropped)}

    def write_chrome_trace(self, path):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def write_jsonl(self, path, append=False):
        """Structured JSONL: one object per event. ``append=True`` writes
        only events not yet flushed to this tracer's stream (the
        incremental ``flush()`` path)."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        start = self._jsonl_flushed if append else 0
        # first incremental flush truncates any stale file from a prior run
        mode = "a" if (append and self._jsonl_flushed > 0) else "w"
        with open(path, mode) as f:
            for e in self.events[start:]:
                f.write(json.dumps(e) + "\n")
        self._jsonl_flushed = len(self.events)
        return path

    def flush(self):
        """Write the configured trace files (no-op without an output dir).
        JSONL appends incrementally; the Chrome trace is rewritten whole so
        the file is always a complete, loadable trace."""
        if not self.enabled or self.output_dir is None:
            return None
        os.makedirs(self.output_dir, exist_ok=True)
        if self.jsonl:
            self.write_jsonl(os.path.join(self.output_dir, "spans.jsonl"),
                             append=True)
        if self.chrome_trace and self._chrome_flushed != len(self.events):
            # the whole-file rewrite is skipped when nothing new arrived:
            # a steps_per_print cadence of no-op flushes must stay O(1)
            self.write_chrome_trace(os.path.join(self.output_dir,
                                                 "trace.json"))
            self._chrome_flushed = len(self.events)
        return self.output_dir
