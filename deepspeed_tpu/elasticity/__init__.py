from .elasticity import (
    compute_elastic_config,
    get_compatible_gpus_v01,
    get_compatible_gpus_v02,
    ElasticityError,
    ElasticityConfig,
)

__all__ = [
    "compute_elastic_config",
    "get_compatible_gpus_v01",
    "get_compatible_gpus_v02",
    "ElasticityError",
    "ElasticityConfig",
]
from .agent import ElasticAgent  # noqa: F401
