"""Elastic batch configuration (reference ``deepspeed/elasticity/elasticity.py``).

Pure scheduling math, ported by behavior: given candidate micro-batch sizes and a
min/max device range, find a total train batch size compatible with as many world
sizes as possible (``compute_elastic_config``, reference ``:233``), so a job can
restart at a different scale (TPU-pod preemption / slice resize) without changing
the effective batch. v0.1 (``:83``) = data-parallel only; v0.2 (``:126``) adds a
model-parallel divisor. Recovery itself is checkpoint-based restart, as in the
reference (``DSElasticAgent`` maps to pod rescheduling + ``jax.distributed``
re-init + checkpoint resume).
"""

import math


class ElasticityError(Exception):
    """Reference ``elasticity/constants.py`` error family."""


class ElasticityConfig:
    """Reference ``elasticity/config.py`` ElasticityConfig (dict-driven)."""

    def __init__(self, param_dict):
        self.enabled = param_dict.get("enabled", False)
        if "max_train_batch_size" not in param_dict:
            raise ElasticityError("Elasticity config missing 'max_train_batch_size'")
        self.max_acceptable_batch_size = int(param_dict["max_train_batch_size"])
        self.micro_batches = [int(m) for m in param_dict.get(
            "micro_batch_sizes", [2, 4, 6])]
        if any(m <= 0 for m in self.micro_batches):
            raise ElasticityError(
                f"micro_batch_sizes must be positive, got {self.micro_batches}")
        self.min_gpus = int(param_dict.get("min_gpus", 1))
        self.max_gpus = int(param_dict.get("max_gpus", 10000))
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityError(
                f"invalid gpu range [{self.min_gpus}, {self.max_gpus}]")
        self.model_parallel_size = int(param_dict.get("model_parallel_size", 1))
        self.num_gpus_per_node = int(param_dict.get("num_gpus_per_node", 1))
        self.min_time = param_dict.get("min_time", 0)
        self.version = float(param_dict.get("version", 0.1))
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch", True)
        self.ignore_non_elastic_batch_info = param_dict.get(
            "ignore_non_elastic_batch_info", False)


def _get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    """All micro-batch * power-of-two accumulations <= cap (reference :33)."""
    candidates = set()
    for base in base_list:
        if base > max_acceptable_batch_size:
            continue
        p = int(math.floor(math.log2(max_acceptable_batch_size / base)))
        for i in range(p + 1):
            candidates.add(base * (2 ** i))
    return sorted(candidates)


def _get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """World sizes w for which some micro-batch divides batch/w (reference :48)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        total_micro = batch_size // mb
        for w in range(1, total_micro + 1):
            if total_micro % w == 0 and min_valid_gpus <= w <= max_valid_gpus:
                valid.add(w)
    return sorted(valid)


def get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size, min_gpus=None,
                            max_gpus=None, prefer_larger=True):
    """Pick (final_batch_size, valid_gpus) maximizing compatibility (reference :83)."""
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)

    candidates = _get_candidate_batch_sizes(micro_batches, max_acceptable_batch_size)
    best = (None, [])
    for bs in candidates:
        valid = _get_valid_gpus(bs, micro_batches, min_gpus, max_gpus)
        better = False
        if len(valid) > len(best[1]):
            better = True
        elif len(valid) == len(best[1]) and best[0] is not None:
            better = (bs > best[0]) if prefer_larger else (bs < best[0])
        if better:
            best = (bs, valid)
    if best[0] is None:
        raise ElasticityError(
            f"No valid batch size found for micro-batches {micro_batches} under "
            f"cap {max_acceptable_batch_size} with gpus in [{min_gpus}, {max_gpus}]")
    return best


def get_compatible_gpus_v02(micro_batches, max_acceptable_batch_size, current_num_gpus,
                            min_gpus=None, max_gpus=None, prefer_larger=True,
                            num_gpus_per_node=1, model_parallel_size=1):
    """v0.2 (reference :126): model parallelism divides the device pool; batch math
    runs over data-parallel groups."""
    if model_parallel_size > 1:
        group_size = model_parallel_size
        if current_num_gpus % group_size:
            raise ElasticityError(
                f"model parallel size {model_parallel_size} must divide device "
                f"count {current_num_gpus}")
        dp = current_num_gpus // group_size
        batch, valid = get_compatible_gpus_v01(
            micro_batches, max_acceptable_batch_size,
            min_gpus=max(1, (min_gpus or 1) // group_size),
            max_gpus=max(1, (max_gpus or current_num_gpus) // group_size),
            prefer_larger=prefer_larger)
        if dp not in valid:
            raise ElasticityError(
                f"current dp world {dp} not in the compatible set {valid}")
        return batch, [v * group_size for v in valid]
    return get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                                   min_gpus=min_gpus, max_gpus=max_gpus,
                                   prefer_larger=prefer_larger)


def compute_elastic_config(ds_config, target_deepspeed_version=None, world_size=0,
                           return_microbatch=False):
    """Reference ``elasticity.py:233``: resolve the elastic section of a config into
    (final_batch_size, valid_gpus[, micro_batch]). With ``world_size`` given, also
    checks compatibility and computes the per-device micro batch."""
    if "elasticity" not in ds_config:
        raise ElasticityError("config is missing the 'elasticity' section")
    cfg = ElasticityConfig(ds_config["elasticity"])
    if not cfg.enabled:
        raise ElasticityError("elasticity section present but not enabled")

    if cfg.version >= 0.2 and cfg.model_parallel_size > 1 and world_size > 0:
        final_batch, valid_gpus = get_compatible_gpus_v02(
            cfg.micro_batches, cfg.max_acceptable_batch_size, world_size,
            min_gpus=cfg.min_gpus, max_gpus=cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch_size,
            num_gpus_per_node=cfg.num_gpus_per_node,
            model_parallel_size=cfg.model_parallel_size)
    else:
        final_batch, valid_gpus = get_compatible_gpus_v01(
            cfg.micro_batches, cfg.max_acceptable_batch_size,
            min_gpus=cfg.min_gpus, max_gpus=cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch_size)

    if world_size > 0:
        dp = world_size // cfg.model_parallel_size if cfg.version >= 0.2 else world_size
        pool = valid_gpus if cfg.version < 0.2 or cfg.model_parallel_size == 1 else [
            v // cfg.model_parallel_size for v in valid_gpus]
        if dp not in pool:
            raise ElasticityError(
                f"world size {world_size} is not compatible with batch "
                f"{final_batch} (valid: {valid_gpus})")
        if return_microbatch:
            per_dev = final_batch // dp
            micro = next((m for m in sorted(cfg.micro_batches, reverse=True)
                          if per_dev % m == 0), None)
            if micro is None:
                raise ElasticityError(
                    f"no configured micro batch divides {per_dev}")
            return final_batch, valid_gpus, micro
    if return_microbatch:
        return final_batch, valid_gpus, min(cfg.micro_batches)
    return final_batch, valid_gpus
