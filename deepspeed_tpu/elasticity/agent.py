"""Elastic agent: preemption-aware training with resume-at-any-scale.

Reference: ``elasticity/elastic_agent.py:28`` ``DSElasticAgent`` — plugs into
torch-elastic's rendezvous to restart jobs when membership changes; recovery is
checkpoint-based. The TPU translation targets how TPU pods actually fail:
preemption arrives as SIGTERM with a grace window. The agent

- wraps the train loop, checkpointing every ``save_interval`` steps (async
  sharded engine — the universal layout is what makes rescaled resume work);
- on SIGTERM/SIGINT it finishes the in-flight step, writes a final
  checkpoint, and returns cleanly (exit-for-restart);
- on (re)start it loads the latest checkpoint INTO WHATEVER MESH the new
  engine has — the index-range-addressed checkpoint reshapes itself, and the
  elastic batch config (``compute_elastic_config``, ported reference math)
  keeps the global batch constant across world sizes.
"""

import os
import signal

from ..utils.logging import log_dist


class ElasticAgent:
    def __init__(self, engine, save_dir, *, save_interval=100, tag_prefix="elastic"):
        self.engine = engine
        self.save_dir = save_dir
        self.save_interval = save_interval
        self.tag_prefix = tag_prefix
        self._preempted = False
        self._prev_handlers = {}

    # -- signals ------------------------------------------------------------
    def _install(self):
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev_handlers[sig] = signal.signal(sig, self._on_signal)

    def _restore(self):
        for sig, h in self._prev_handlers.items():
            signal.signal(sig, h)
        self._prev_handlers = {}

    def _on_signal(self, signum, frame):
        log_dist(f"ElasticAgent: received signal {signum}; will checkpoint "
                 f"and stop after the current step", ranks=[0])
        self._preempted = True

    # -- checkpoint plumbing ------------------------------------------------
    def _tag(self):
        return f"{self.tag_prefix}-step{self.engine.global_steps}"

    def save(self):
        self.engine.save_checkpoint(self.save_dir, tag=self._tag())

    def try_resume(self):
        """Load the newest checkpoint if one exists; reshapes to the current
        engine's mesh automatically. Returns the restored step (or 0)."""
        latest = os.path.join(self.save_dir, "latest")
        if not os.path.exists(latest):
            return 0
        self.engine.load_checkpoint(self.save_dir)
        log_dist(f"ElasticAgent: resumed at step {self.engine.global_steps} "
                 f"on mesh {dict(self.engine.mesh.shape)}", ranks=[0])
        return self.engine.global_steps

    # -- the loop -----------------------------------------------------------
    def run(self, data_iter, total_steps):
        """Train until ``total_steps`` or preemption. Returns
        ("finished" | "preempted", steps_done)."""
        self._install()
        try:
            start = self.engine.global_steps
            for _ in range(start, total_steps):
                batch = next(data_iter)
                self.engine.train_batch(batch=batch)
                if self.engine.global_steps % self.save_interval == 0:
                    self.save()
                if self._preempted:
                    self.save()
                    return "preempted", self.engine.global_steps
            self.save()
            return "finished", self.engine.global_steps
        finally:
            self._restore()
