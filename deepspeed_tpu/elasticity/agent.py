"""Elastic agent: preemption-aware training with resume-at-any-scale.

Reference: ``elasticity/elastic_agent.py:28`` ``DSElasticAgent`` — plugs into
torch-elastic's rendezvous to restart jobs when membership changes; recovery is
checkpoint-based. The TPU translation targets how TPU pods actually fail:
preemption arrives as SIGTERM with a grace window. The agent

- wraps the train loop, checkpointing every ``save_interval`` steps (async
  sharded engine — the universal layout is what makes rescaled resume work);
- on SIGTERM/SIGINT it finishes the in-flight step, writes a final
  checkpoint, and returns cleanly (exit-for-restart);
- on (re)start it loads the latest checkpoint INTO WHATEVER MESH the new
  engine has — the index-range-addressed checkpoint reshapes itself, and the
  elastic batch config (``compute_elastic_config``, ported reference math)
  keeps the global batch constant across world sizes;
- resume walks the **recovery chain**: if ``latest`` names a missing or
  corrupt tag (preempted mid-save, torn write, bit rot), the bad tag is
  quarantined to ``<tag>.corrupt`` and the next-newest COMMITTED checkpoint
  is tried, until one verifies and loads — a preempted pod can always
  restart from *some* valid state;
- ``keep_last=N`` prunes the oldest committed tags after each save so
  preemption-heavy runs don't fill the disk (the newest valid checkpoint is
  never pruned).
"""

import os
import shutil
import signal

from ..checkpoint import atomic
from ..utils.logging import log_dist, logger


class ElasticAgent:
    def __init__(self, engine, save_dir, *, save_interval=100,
                 tag_prefix="elastic", keep_last=None, clock=None):
        self.engine = engine
        self.save_dir = save_dir
        self.save_interval = save_interval
        self.tag_prefix = tag_prefix
        # overlapped snapshots (checkpoint/snapshot.py), armed by the
        # engine's `elastic` config block: the shadow capture + background
        # writer replace the synchronous save_interval saves, and the
        # SIGTERM path commits the freshest shadow inside the grace window
        self.snapshots = None
        ecfg = getattr(getattr(engine, "config", None), "elastic", None)
        if ecfg is not None and ecfg.enabled:
            ckpt_cfg = getattr(engine.config, "checkpoint", None)
            if ckpt_cfg is not None and ckpt_cfg.engine != "sharded":
                from ..config import ConfigError

                # the snapshot writer emits the sharded layout; resuming it
                # through an npz engine would fail every tag and the
                # recovery chain would then QUARANTINE the healthy
                # snapshots — reject the combination up front
                raise ConfigError(
                    f"elastic.enabled requires checkpoint.engine='sharded' "
                    f"(got {ckpt_cfg.engine!r}): overlapped snapshots write "
                    f"the sharded/universal layout")
            from ..checkpoint.snapshot import SnapshotManager

            self.snapshots = SnapshotManager(
                engine, save_dir, cfg=ecfg, tag_prefix=tag_prefix,
                clock=clock)
            if keep_last is None:
                keep_last = ecfg.keep_last
        if keep_last is not None and keep_last < 1:
            raise ValueError("keep_last must be >= 1 (the newest valid "
                             "checkpoint is never pruned)")
        self.keep_last = keep_last
        self.preemptions = 0
        self.resumes_rescaled = 0
        self._preempted = False
        self._torn_down = False
        self._signum = None
        self._prev_handlers = {}

    # -- signals ------------------------------------------------------------
    def _install(self):
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev_handlers[sig] = signal.signal(sig, self._on_signal)

    def _restore(self):
        for sig, h in self._prev_handlers.items():
            signal.signal(sig, h)
        self._prev_handlers = {}

    def _on_signal(self, signum, frame):
        """Record the preemption and return — the handler itself does no
        I/O. The run loop finishes the in-flight step, then walks the ONE
        ordered teardown path: checkpoint commit -> health dump -> exit
        (``_teardown``), so the black box can never race the grace-window
        flush and nothing dumps twice."""
        log_dist(f"ElasticAgent: received signal {signum}; will checkpoint "
                 f"and stop after the current step", ranks=[0])
        self._preempted = True
        self._torn_down = False
        self._signum = signum

    def _teardown(self):
        """Ordered preemption teardown after the in-flight step: (1) commit
        the freshest state — the overlapped-snapshot flush when armed (only
        the not-yet-written remainder), else a full synchronous save; (2)
        publish the health black box; (3) hand control back. A checkpoint
        failure must not swallow the dump — the finally does (2) on the way
        out of a raising (1)."""
        self._torn_down = True
        self.preemptions += 1
        try:
            if self.snapshots is not None:
                try:
                    self.snapshots.flush("preempt")
                except Exception as e:
                    logger.warning(
                        "ElasticAgent: snapshot flush failed (%s) — falling "
                        "back to a synchronous save", e)
                    try:
                        # quiesce the background writer first: the sync save
                        # may reuse the very tag a live writer is staging
                        self.snapshots.close()
                    except Exception:
                        pass
                    self.save()
                else:
                    self._prune_if_configured()
            else:
                self.save()
            self._emit([("Elastic/preemptions", float(self.preemptions),
                         self.engine.global_steps)])
        finally:
            health = getattr(self.engine, "health", None)
            if (health is not None and health.enabled
                    and getattr(health.cfg, "dump_on_signal", True)):
                health.dump(f"signal{self._signum}")

    def _emit(self, events):
        mon = getattr(self.engine, "monitor", None)
        if mon is not None and getattr(mon, "enabled", False):
            mon.write_events(events)

    # -- checkpoint plumbing ------------------------------------------------
    def _tag(self):
        return f"{self.tag_prefix}-step{self.engine.global_steps}"

    def save(self):
        self.engine.save_checkpoint(self.save_dir, tag=self._tag())
        self._prune_if_configured()

    def _prune_if_configured(self):
        if self.keep_last is not None:
            self._prune()

    def _committed_step(self):
        """Step of the newest COMMITTED checkpoint — the ``latest``
        pointer's target (the pointer swap IS the commit record)."""
        tag = atomic.read_latest(self.save_dir)
        if tag is None:
            return None
        marker = atomic.read_marker(os.path.join(self.save_dir, tag))
        step = marker.get("step") if marker else None
        return step if isinstance(step, (int, float)) else None

    def _prune(self):
        """Retention: drop this agent's committed tags (``<tag_prefix>-*``)
        beyond the newest ``keep_last`` *valid* ones — never tags some other
        writer put in the same save_dir. Uncommitted stages and quarantined dirs are left for
        fsck; the newest valid checkpoint always survives. Multi-process:
        only process 0 mutates the shared directory (save_checkpoint's
        commit barrier has already fenced every rank's shards).

        Race fence vs the overlapped-snapshot writer: a snapshot tag is
        PUBLISHED by the background thread before the ``latest`` swap makes
        it the commit point — counting such a tag toward ``keep_last`` could
        push the last *committed* one over the retention edge, leaving
        ``latest`` dangling if the fresh tag's commit then fails. Anything
        newer than the last committed step, anything the live writer still
        owns, and ``.tmp`` stages (excluded by ``list_tags``) are therefore
        off-limits; retention only ever counts committed history."""
        import jax

        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        prefix = self.tag_prefix + "-"
        committed = self._committed_step()
        live = self.snapshots.live_tags if self.snapshots is not None else ()
        valid = []
        for tag in atomic.list_tags(self.save_dir, newest_first=True):
            if not tag.startswith(prefix):
                continue  # not ours: a shared save_dir may hold user tags
            if tag in live:
                continue  # the background writer still owns this stage
            path = os.path.join(self.save_dir, tag)
            ok, _ = atomic.verify_checkpoint_dir(path, deep=False)
            if not ok:
                continue
            marker = atomic.read_marker(path)
            step = marker.get("step") if marker else None
            if committed is not None and isinstance(step, (int, float)) \
                    and step > committed:
                continue  # published but not yet committed: never touch
            valid.append(tag)
        for tag in valid[self.keep_last:]:
            path = os.path.join(self.save_dir, tag)
            log_dist(f"ElasticAgent: pruning old checkpoint {tag} "
                     f"(keep_last={self.keep_last})", ranks=[0])
            shutil.rmtree(path, ignore_errors=True)

    def _walk_candidates(self):
        """Shallow ordering pass over the resume chain (marker presence +
        file sizes only — deep CRC verification happens lazily in
        ``try_resume`` right before a candidate is loaded, so a restart pays
        one full read of ONE checkpoint, not of every retained tag). Returns
        ``(verified, legacy, skipped)``: marker-bearing tags in resume order,
        marker-less pre-protocol tags demoted behind them, and ``(tag,
        reason)`` pairs for everything quarantined."""
        verified, legacy, skipped = [], [], []
        for tag in atomic.resume_candidates(self.save_dir):
            path = os.path.join(self.save_dir, tag)
            if atomic.read_marker(path) is None:
                legacy.append(tag)  # pre-protocol save: unverifiable, not corrupt
                continue
            ok, reason = atomic.verify_checkpoint_dir(path, deep=False)
            if not ok:
                skipped.append((tag, reason))
                # "unverifiable" = transient I/O, not proof of corruption —
                # skip it this restart but leave the data in place
                if not atomic.is_transient_verify_failure(reason):
                    atomic.quarantine(path)
                continue
            verified.append(tag)
        return verified, legacy, skipped

    def try_resume(self):
        """Resume from the newest *valid* checkpoint; reshapes to the current
        engine's mesh automatically. Returns the restored step (or 0).

        Walks the recovery chain: the ``latest`` pointer's target first, then
        every other published tag newest-first; marker-less (pre-protocol)
        checkpoints are demoted to last-resort candidates rather than treated
        as corrupt. Quarantine to ``<tag>.corrupt`` happens only on *proven*
        corruption (checksum/size mismatch, missing files, or a corruption
        error during load) — never for legacy layouts, transient I/O errors,
        or shape-incompatible-but-intact checkpoints — and the walk
        continues, so a stale or torn ``latest`` never prevents restart.
        """
        import jax

        from ..checkpoint.atomic import CheckpointCorruptionError
        from ..utils.retry import io_retry_policy, retry_call

        multi = jax.process_count() > 1
        if multi:
            # filesystem decisions (verify/quarantine/candidate order) must be
            # made ONCE — per-rank walks would quarantine dirs out from under
            # each other's collective load. Process 0 decides, everyone loads.
            from .. import comm as dist

            order = self._walk_candidates() \
                if jax.process_index() == 0 else None
            verified, legacy, skipped = dist.broadcast_obj(order, src=0)
        else:
            verified, legacy, skipped = self._walk_candidates()
        decides = not multi or jax.process_index() == 0
        tainted = False
        for tag in verified + legacy:
            path = os.path.join(self.save_dir, tag)
            if tag not in legacy:
                # deep-CRC only the candidate about to be loaded — not the
                # whole retained chain (legacy tags have nothing to check)
                res = atomic.verify_checkpoint_dir(path) if decides else None
                ok, reason = dist.broadcast_obj(res, src=0) if multi else res
                if not ok:
                    skipped.append((tag, reason))
                    if decides and \
                            not atomic.is_transient_verify_failure(reason):
                        atomic.quarantine(path)
                    continue
            corrupt = False
            try:
                # verify=False: this tag was just deep-checksummed above.
                # Reuse the policy the engine was configured with
                # (checkpoint.retries / retry_backoff), not the env defaults
                retry_call(self.engine.load_checkpoint, self.save_dir,
                           tag=tag, verify=False,
                           policy=getattr(self.engine.checkpoint_engine,
                                          "_retry", None) or io_retry_policy(),
                           describe=f"resume load {tag}")
                loaded, err = True, None
            except Exception as e:
                loaded, err = False, e
                corrupt = isinstance(e, CheckpointCorruptionError)
            if multi:
                # one host failing its shard read must fail the whole group,
                # or ranks resume from DIFFERENT tags and silently diverge
                group_ok = dist.all_agree(loaded)
                # a locally-loaded but group-rejected tag left this rank's
                # engine holding that tag's state; a later successful load
                # fully overwrites it, but if the chain ends here the ranks
                # are divergent — remember, and fail loudly at the end
                tainted = tainted or (loaded and not group_ok)
                loaded = group_ok
            if not loaded:
                skipped.append(
                    (tag, f"load failed: {err or 'on another process'}"))
                # quarantine only proven corruption (never shape changes or
                # transient I/O), only by the deciding process, and only
                # after every rank has exited the load (the consensus above
                # is the fence) — keep unloadable-but-intact data around
                if corrupt and decides:
                    atomic.quarantine(path)
                continue
            if skipped:
                logger.warning(
                    "ElasticAgent: skipped %d corrupt checkpoint(s) on "
                    "resume: %s", len(skipped),
                    "; ".join(f"{t} ({r})" for t, r in skipped))
            if getattr(self.engine, "_last_resume_rescaled", False):
                # the checkpoint was written on a different mesh and the
                # universal layout resharded it onto this one — observable,
                # not assumed
                self.resumes_rescaled += 1
                self._emit([("Elastic/resumes_rescaled",
                             float(self.resumes_rescaled),
                             self.engine.global_steps)])
            log_dist(f"ElasticAgent: resumed at step {self.engine.global_steps} "
                     f"on mesh {dict(self.engine.mesh.shape)}", ranks=[0])
            return self.engine.global_steps
        if multi and not dist.all_agree(not tainted):
            # some rank still holds a group-rejected tag's loaded state while
            # others hold fresh init — "resume from step 0" would silently
            # diverge. Every rank raises together; a restart re-walks cleanly.
            from ..checkpoint.atomic import CheckpointError

            raise CheckpointError(
                "resume chain exhausted after a group-rejected load left "
                "process state inconsistent across ranks — restart the job")
        if skipped:
            logger.warning(
                "ElasticAgent: no valid checkpoint found under %s (%d "
                "quarantined: %s) — starting from step 0", self.save_dir,
                len(skipped), "; ".join(f"{t} ({r})" for t, r in skipped))
        return 0

    # -- the loop -----------------------------------------------------------
    def run(self, data_iter, total_steps):
        """Train until ``total_steps`` or preemption. Returns
        ("finished" | "preempted", steps_done).

        With the elastic snapshot path armed, the shadow capture runs after
        every step (on the budgeted cadence) and ``save_interval`` marks the
        periodic COMMIT cadence (a flush: join the writer + pointer swap) —
        the synchronous full save only remains for the non-elastic mode."""
        self._install()
        try:
            start = self.engine.global_steps
            try:
                for _ in range(start, total_steps):
                    batch = next(data_iter)
                    self.engine.train_batch(batch=batch)
                    if self.snapshots is not None:
                        if self.snapshots.maybe_snapshot():
                            # the writer commits each published snapshot, so
                            # retention can run on the capture cadence
                            # instead of letting tags pile up to the next
                            # periodic flush
                            self._prune_if_configured()
                        if self.engine.global_steps % self.save_interval == 0:
                            self.snapshots.flush("periodic")
                            self._prune_if_configured()
                    elif self.engine.global_steps % self.save_interval == 0:
                        self.save()
                    if self._preempted:
                        self._teardown()
                        return "preempted", self.engine.global_steps
            except BaseException:
                if self._preempted and not self._torn_down:
                    # the preemption arrived but the loop died before the
                    # normal teardown (e.g. the data iterator raised):
                    # still spend the grace window on the ordered
                    # commit -> dump path before propagating
                    self._teardown()
                raise
            if self.snapshots is not None:
                self.snapshots.finalize("final")
                self._prune_if_configured()
            else:
                self.save()
            return "finished", self.engine.global_steps
        finally:
            self._restore()
