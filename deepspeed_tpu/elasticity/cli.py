"""``ds_tpu_elastic`` — elastic batch calculator CLI (reference ``bin/ds_elastic``):
resolve a config's elasticity section into the final batch size, the
compatible device counts, and (optionally) the per-device micro batch at a
given world size.

    ds_tpu_elastic -c ds_config.json
    ds_tpu_elastic -c ds_config.json -w 64
"""

import argparse
import json
import sys

from .elasticity import ElasticityError, compute_elastic_config


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-c", "--config", required=True,
                   help="DeepSpeed config JSON with an elasticity section")
    p.add_argument("-w", "--world-size", type=int, default=0,
                   help="also validate this device count and derive the "
                        "micro batch")
    args = p.parse_args(argv)

    ds_config = json.load(open(args.config))
    try:
        if args.world_size > 0:
            batch, valid, micro = compute_elastic_config(
                ds_config, world_size=args.world_size, return_microbatch=True)
        else:
            batch, valid = compute_elastic_config(ds_config)
            micro = None
    except ElasticityError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1

    print(f"final train_batch_size : {batch}")
    print(f"compatible device counts: {sorted(valid)}")
    if micro is not None:
        print(f"micro batch @ world={args.world_size}: {micro}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
