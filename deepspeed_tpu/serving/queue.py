"""Bounded FCFS request queue with load-aware admission control.

The queue is the backpressure point: depth is bounded, and a request that
would exceed the bound (or could never fit a slot's KV window) is shed at
submit time with a machine-readable reason — the serving layer degrades by
rejecting work, never by growing host/device memory until it falls over.
"""

import collections

from .request import (CLASS_BATCH, CLASS_INTERACTIVE, REJECT_BAD_REQUEST,
                      REJECT_NO_FREE_BLOCKS, REJECT_PROMPT_TOO_LONG,
                      REJECT_QUEUE_FULL, RequestState)


class RequestQueue:
    def __init__(self, max_depth):
        self.max_depth = int(max_depth)
        self._q = collections.deque()
        self.shed_counts = collections.Counter()

    def __len__(self):
        return len(self._q)

    @property
    def depth(self):
        return len(self._q)

    def admit(self, request, max_total_len, kv_fits=None):
        """Admission control: accept ``request`` into the queue or shed it.

        Returns None on admission; on shed, marks the request REJECTED and
        returns the reason string. ``max_total_len`` is the per-slot KV
        window that prompt + generation must fit. ``kv_fits`` (paged KV
        pool): (prompt_len, max_new_tokens) -> bool — False means the
        request's block footprint exceeds what the pool could EVER free, so
        queueing it would wait forever: shed ``no_free_blocks`` now."""
        reason = None
        if request.prompt_len < 1 or request.max_new_tokens < 1 \
                or request.tenant_class not in (CLASS_INTERACTIVE,
                                                CLASS_BATCH):
            reason = REJECT_BAD_REQUEST
        elif request.prompt_len + request.max_new_tokens > max_total_len:
            reason = REJECT_PROMPT_TOO_LONG
        elif kv_fits is not None and not kv_fits(request.prompt_len,
                                                request.max_new_tokens):
            reason = REJECT_NO_FREE_BLOCKS
        elif len(self._q) >= self.max_depth:
            reason = REJECT_QUEUE_FULL
        if reason is not None:
            request.state = RequestState.REJECTED
            request.reject_reason = reason
            self.shed_counts[reason] += 1
            return reason
        request.state = RequestState.QUEUED
        self._q.append(request)
        return None

    def pop(self):
        return self._q.popleft()

    def pop_at(self, index):
        """Remove and return the request at ``index`` (head-of-line bypass:
        the scheduler admits a later request past a blocked head under its
        bounded-starvation window)."""
        req = self._q[index]
        del self._q[index]
        return req

    def peek(self):
        return self._q[0] if self._q else None

    def peek_at(self, index):
        return self._q[index]

    def push_front(self, request):
        """Re-queue an ALREADY-ADMITTED request at the head (on-demand-growth
        preemption: the request was running, so it outranks everything queued
        behind it — FCFS by original admission order). Bypasses admission
        control: its footprint passed ``fits_ever`` at submit and depth
        bounds protect arrivals, not returners."""
        request.state = RequestState.QUEUED
        self._q.appendleft(request)
