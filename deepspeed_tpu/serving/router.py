"""Multi-replica serving router: the tier above the engine.

One ``ServingEngine`` is one replica; the "millions of users" topology is N
replicas behind a load-aware dispatcher (the DeepSpeed-Inference serving-tier
shape, arXiv:2207.00032). The router extends the single-replica
shed-with-reason admission control into cross-replica balancing:

- **Load-aware dispatch.** Replicas are scored on queue depth, slot
  occupancy and paged-block occupancy (the same signals
  ``ServingMetrics.snapshot()`` reports); ``least_loaded`` picks the
  arg-min, ``round_robin`` cycles. A request is only offered to replicas
  with queue room — when every live replica is saturated the router sheds
  ``all_replicas_saturated`` instead of letting one replica OOM its queue.
- **Session & prefix affinity.** Requests with a ``session_id`` stick to
  one replica. Stateless requests are matched against a shared prefix
  index: the paged pool's SHA-256 prefix chain keys (``kv_pool.
  prefix_chain_keys``) mapped to the replica that last served them, so an
  identical system prompt routes to the replica whose blocks already hold
  its prefix (suffix-only prefill there). An affinity target that is
  overloaded relative to the best candidate is overridden (a *rebalance*).
- **Drain / rejoin.** ``drain(i)`` stops new admissions to a replica while
  its in-flight requests finish (the PR 11 teardown discipline: quiesce,
  then tear down); ``rejoin(i)`` re-registers it (optionally with a fresh
  engine after a restart, which purges its affinity state).

Everything is host-side policy over per-replica virtual (or wall) clocks, so
the whole topology is assertable in tier-1: ``serve()`` runs a conservative
discrete-event simulation — always stepping the replica whose local clock is
furthest behind — which makes N "parallel" replicas exactly reproducible on
one process.
"""

import collections
import os

from ..telemetry.digest import LatencyDigest, evaluate_slo
from .clock import VirtualClock
from .control import Autoscaler, BurnSensor
from .kv_pool import prefix_chain_keys
from .metrics import percentile, slo_digest_events
from .migration import advance_rng
from .request import (FINISH_UNHEALTHY, REJECT_ALL_REPLICAS_SATURATED,
                      REJECT_REPLICA_FAILED, RequestState, TokenEvent,
                      as_request)


class _Replica:
    """Router-side replica handle: the engine plus drain/health state."""

    def __init__(self, sv, idx=0):
        self.sv = sv
        self.idx = idx
        self.draining = False
        # failure-recovery state machine: "live" -> "degraded" (stalled —
        # its clock jumped ahead, the DES starves it until the fleet
        # catches up; still correct, still routable) -> "dead" (killed:
        # in-flight work failed over to survivors, never routed again)
        self.health = "live"
        self.stall_until = 0.0
        # disaggregated-fleet role (serving.pools): "mixed" (default) |
        # "prefill" | "decode" — assigned at Router construction
        self.role = "mixed"

    @property
    def dead(self):
        return self.health == "dead"

    @property
    def busy(self):
        return bool(self.sv._slots or self.sv.queue.depth
                    or self.sv._prefill_jobs)

    @property
    def saturated(self):
        """Submitting now would shed ``queue_full``."""
        return self.sv.queue.depth >= self.sv.cfg.max_queue_depth

    def load_score(self, cfg):
        sv = self.sv
        score = cfg.queue_weight * sv.queue.depth \
            / max(sv.cfg.max_queue_depth, 1)
        score += cfg.slot_weight \
            * (len(sv._slots) + len(sv._prefill_jobs)) / max(sv.n_slots, 1)
        if sv.paged:
            # O(1) accessor, not the full stats() dict: this runs per
            # routed request per live replica
            score += cfg.block_weight * sv.pool_mgr.occupancy()
        return score

    def prefill_score(self, cfg):
        """Prefill-pool dispatch score: queue depth + PENDING PROMPT
        TOKENS (queued prompts plus in-flight prefill-job remainders,
        normalized by the pool's token capacity) — slot/block occupancy is
        the wrong signal for a pool whose slots recycle at first-token
        time; what queues work here is un-prefilled prompt length."""
        sv = self.sv
        score = cfg.queue_weight * sv.queue.depth \
            / max(sv.cfg.max_queue_depth, 1)
        pending = sum(r.prompt_len for r in sv.queue._q)
        pending += sum(len(j.ids) - j.pos for j in sv._prefill_jobs)
        score += pending / max(sv.n_slots * sv.max_len, 1)
        return score

    def decode_score(self, cfg):
        """Decode-pool dispatch score: slot + paged-block occupancy only
        (a decode replica's queue holds just splices in flight — imminent
        slots, so they count toward batch fullness: a score blind to them
        would see a just-landed move as free capacity and the rebalancer
        would oscillate instead of settling inside the hysteresis band)."""
        sv = self.sv
        score = cfg.slot_weight * (len(sv._slots) + sv.queue.depth) \
            / max(sv.n_slots, 1)
        if sv.paged:
            score += cfg.block_weight * sv.pool_mgr.occupancy()
        return score

    def pool_score(self, cfg):
        """The role-appropriate dispatch score."""
        if self.role == "prefill":
            return self.prefill_score(cfg)
        if self.role == "decode":
            return self.decode_score(cfg)
        return self.load_score(cfg)


class RouterMetrics:
    """Cross-replica counters + the Serving/router_* monitor events.

    ``snapshot()`` is the machine-readable rollup (the bench artifact's
    ``router`` block); ``emit_events`` writes the same numbers through the
    existing MonitorMaster fan-out — tier-1 asserts the two stay coherent
    (the PR 4 trace==metrics discipline, router edition)."""

    def __init__(self, router, monitor=None, interval=32):
        self._router = router
        self.monitor = monitor
        self.interval = max(int(interval), 1)
        self._loop_calls = 0
        self.routed = 0
        self.shed_saturated = 0
        self.session_hits = 0
        self.prefix_hits = 0
        self.prefix_lookups = 0
        self.rebalances = 0
        self.drains = 0
        self.rejoins = 0
        # failure recovery, each counted distinctly: cross-replica
        # re-dispatches after a replica death, unhealthy_slot retries on a
        # different replica, terminal replica_failed sheds, and the raw
        # fault counts the chaos schedule fired
        self.failovers = 0
        self.retries = 0
        self.shed_replica_failed = 0
        self.replica_kills = 0
        self.replica_stalls = 0
        # disaggregated fleet: completed first-token prefill->decode
        # handoffs, and live rebalance moves (voluntary mid-flight stream
        # migrations off hot replicas — distinct from ``rebalances``
        # above, which counts affinity overrides at ROUTING time)
        self.handoffs = 0
        self.pool_rebalances = 0
        # cumulative replica scheduler steps — the autoscaler acceptance
        # currency: a parked replica steps zero times, so a right-sized
        # fleet's total is strictly below an always-max static fleet's
        self.replica_steps = 0
        self.per_replica_routed = collections.Counter()
        self._events_emitted = 0
        # fleet-level SLO bookkeeping (emit intervals with >=1 violated
        # target, mirroring ServingMetrics.slo_violations per replica)
        self.slo_violations = 0

    @property
    def affinity_hit_rate(self):
        """Prefix-affinity hit rate: routed-by-prefix / prefix lookups."""
        return self.prefix_hits / self.prefix_lookups \
            if self.prefix_lookups else 0.0

    # ------------------------------------------------- fleet-merged rollups
    def fleet_digests(self):
        """Fleet latency digests: the EXACT merge of every replica's
        (integer bucket addition — associative, so the fleet percentile is
        independent of replica count and merge order)."""
        reps = self._router._replicas
        return {name: LatencyDigest.merged(
            [r.sv.metrics.latency_digests()[name] for r in reps])
            for name in ("ttft", "tpot", "queue_wait")}

    def fleet_goodput(self):
        """Fleet goodput: replica token counters summed (same currency)."""
        reps = self._router._replicas
        keys = ("prefill_device_tokens", "decode_tokens", "replay_tokens",
                "padding_tokens", "prefix_saved_tokens")
        tot = {k: sum(getattr(r.sv.metrics, k) for r in reps) for k in keys}
        total = tot["prefill_device_tokens"] + tot["decode_tokens"]
        wasted = tot["replay_tokens"] + tot["padding_tokens"]
        tot["wasted_tokens"] = wasted
        tot["goodput_frac"] = round((total - wasted) / total, 4) \
            if total else 1.0
        return tot

    def fleet_migration(self):
        """Fleet live-migration rollup: replica snapshot/splice counters
        summed, plus the router-side recovery counts — the ``resilience``
        block bench artifacts commit."""
        reps = self._router._replicas
        keys = ("kv_snapshots", "migrations_out", "migrations_in",
                "migrated_saved_tokens")
        out = {k: sum(getattr(r.sv.metrics, k) for r in reps) for k in keys}
        out["failovers"] = self.failovers
        out["retries"] = self.retries
        out["shed_replica_failed"] = self.shed_replica_failed
        out["replica_kills"] = self.replica_kills
        out["replica_stalls"] = self.replica_stalls
        return out

    def fleet_slo(self, digests=None):
        """``digests``: pass an already-merged ``fleet_digests()`` result to
        avoid re-merging (snapshot() runs on per-replica hooks)."""
        return evaluate_slo(
            self._router._slo.targets_ms() if self._router._slo is not None
            else {}, digests if digests is not None else self.fleet_digests())

    def fleet_tenancy(self):
        """Fleet per-tenant rollup: every replica's tenant counters summed
        and tenant digests exact-merged (same associative bucket addition
        as ``fleet_digests``), then graded against the tenant class's SLO
        targets — the ``tenancy`` block of fleet.json / bench artifacts."""
        reps = self._router._replicas
        merged = {}
        grader = None
        for r in reps:
            m = r.sv.metrics
            if m.tenants_cfg is not None:
                grader = m
            for tid, t in m.tenants.items():
                g = merged.get(tid)
                if g is None:
                    g = merged[tid] = {
                        "class": t["class"], "submitted": 0, "finished": 0,
                        "tokens": 0, "shed": collections.Counter(),
                        "ttft": LatencyDigest(), "tpot": LatencyDigest(),
                    }
                g["submitted"] += t["submitted"]
                g["finished"] += t["finished"]
                g["tokens"] += t["tokens"]
                g["shed"].update(t["shed"])
                g["ttft"].merge(t["ttft_digest"])
                g["tpot"].merge(t["tpot_digest"])
        if grader is None and reps:
            grader = reps[0].sv.metrics
        out = {}
        for tid in sorted(merged):
            g = merged[tid]
            digests = {"ttft": g["ttft"], "tpot": g["tpot"]}
            out[tid] = {
                "class": g["class"],
                "submitted": g["submitted"],
                "finished": g["finished"],
                "shed": dict(g["shed"]),
                "tokens": g["tokens"],
                "ttft_p99_ms": g["ttft"].quantile_ms(99),
                "tpot_p99_ms": g["tpot"].quantile_ms(99),
                "slo": evaluate_slo(
                    grader.tenant_slo_targets(g["class"]), digests),
            }
        return out

    def pool_rollup(self):
        """Per-pool topology rollup: routed counts, mean occupancy and the
        TTFT split by pool (a handed-off stream's first token fires on its
        PREFILL replica, so pool membership of the recording replica is
        the attribution) — the bench artifact's ``topology`` block."""
        reps = self._router._replicas
        to_ms = lambda v: None if v is None else v * 1e3
        out = {"enabled": self._router._pools_on,
               "roles": [r.role for r in reps]}
        for role in ("prefill", "decode", "mixed"):
            members = [r for r in reps if r.role == role]
            if not members:
                continue
            ttft = [s for r in members for s in r.sv.metrics.ttft_samples]
            out[role] = {
                "replicas": [r.idx for r in members],
                "routed": sum(self.per_replica_routed[r.idx]
                              for r in members),
                "occupancy": round(sum(
                    r.sv.pool_mgr.occupancy() if r.sv.paged else
                    len(r.sv._slots) / max(r.sv.n_slots, 1)
                    for r in members) / len(members), 4),
                "ttft_ms": {"p50": to_ms(percentile(ttft, 50)),
                            "p99": to_ms(percentile(ttft, 99))},
            }
        return out

    def snapshot(self):
        reps = self._router._replicas
        return {
            "replicas": len(reps),
            "routed": self.routed,
            "per_replica_routed": [self.per_replica_routed[i]
                                   for i in range(len(reps))],
            "per_replica_queue_depth": [r.sv.queue.depth for r in reps],
            "per_replica_active_slots": [len(r.sv._slots) for r in reps],
            "per_replica_occupancy": [
                round(r.sv.pool_mgr.occupancy(), 4) if r.sv.paged else
                round(len(r.sv._slots) / max(r.sv.n_slots, 1), 4)
                for r in reps],
            "draining": [i for i, r in enumerate(reps) if r.draining],
            "health": [r.health for r in reps],
            "migration": self.fleet_migration(),
            "session_hits": self.session_hits,
            "prefix_hits": self.prefix_hits,
            "prefix_lookups": self.prefix_lookups,
            "affinity_hit_rate": round(self.affinity_hit_rate, 4),
            "rebalances": self.rebalances,
            "drains": self.drains,
            "rejoins": self.rejoins,
            "shed_all_replicas_saturated": self.shed_saturated,
            # disaggregated topology: pool roles + the handoff/rebalance
            # counters (coherent with Serving/handoffs|rebalances events)
            "roles": [r.role for r in reps],
            "handoffs": self.handoffs,
            "pool_rebalances": self.pool_rebalances,
            "pools": self.pool_rollup(),
            "replica_steps": self.replica_steps,
        }

    def maybe_emit(self):
        """Rate-limited emit for the serve/step loops (every ``interval``
        scheduler rounds, mirroring ServingMetrics.monitor_interval)."""
        self._loop_calls += 1
        if self.monitor is not None and self._loop_calls % self.interval == 0:
            self.emit_events()

    def emit_events(self):
        """Serving/router_* scalars through the monitor fan-out — one event
        stream per scalar, per-replica queue depths suffixed _r<i>."""
        if self.monitor is None:
            return
        self._events_emitted += 1
        step = self._events_emitted
        snap = self.snapshot()
        events = [
            ("Serving/router_routed", float(snap["routed"]), step),
            ("Serving/router_affinity_hit_rate",
             float(snap["affinity_hit_rate"]), step),
            ("Serving/router_rebalances", float(snap["rebalances"]), step),
            ("Serving/router_drains", float(snap["drains"]), step),
            ("Serving/router_sheds",
             float(snap["shed_all_replicas_saturated"]), step),
            # fleet recovery scalars (live KV migration + failover): the
            # committed Serving/migrations / Serving/failovers streams
            ("Serving/migrations",
             float(snap["migration"]["migrations_in"]), step),
            ("Serving/failovers", float(snap["migration"]["failovers"]),
             step),
            ("Serving/router_retries",
             float(snap["migration"]["retries"]), step),
            ("Serving/router_shed_replica_failed",
             float(snap["migration"]["shed_replica_failed"]), step),
            # disaggregated topology: first-token handoffs + live rebalance
            # moves, the same numbers snapshot() reports (tier-1 coherence)
            ("Serving/handoffs", float(snap["handoffs"]), step),
            ("Serving/rebalances", float(snap["pool_rebalances"]), step),
        ]
        if snap["pools"]["enabled"]:
            for role in ("prefill", "decode"):
                pool = snap["pools"].get(role)
                if pool is None:
                    continue
                events.append((f"Serving/pool_{role}_routed",
                               float(pool["routed"]), step))
                events.append((f"Serving/pool_{role}_occupancy",
                               float(pool["occupancy"]), step))
        for i, depth in enumerate(snap["per_replica_queue_depth"]):
            events.append((f"Serving/router_r{i}_queue_depth", float(depth),
                           step))
        for i, occ in enumerate(snap["per_replica_occupancy"]):
            events.append((f"Serving/router_r{i}_occupancy", float(occ),
                           step))
        # fleet-merged digest P99s / goodput / SLO grade, same event names
        # as the per-replica cadence (this monitor sees the FLEET numbers —
        # the acceptance pin reads Serving/ttft_p99_ms here)
        goodput = self.fleet_goodput()
        events.extend(slo_digest_events(
            self.fleet_digests(), goodput["goodput_frac"],
            self._router._slo, step, tracer=self._router.tracer,
            counter=self))
        self.monitor.write_events(events)


class Router:
    """Load-aware dispatcher over N ``ServingEngine`` replicas."""

    def __init__(self, replicas, config=None, monitor=None, tracer=None):
        if not replicas:
            raise ValueError("Router needs at least one replica")
        self.cfg = config if config is not None else replicas[0].cfg.router
        self._replicas = [_Replica(sv, i) for i, sv in enumerate(replicas)]
        self._sessions = {}                        # session_id -> replica idx
        self._prefix_index = collections.OrderedDict()  # chain key -> idx
        self._rr_next = 0
        self._next_id = 0
        # failure recovery: in-flight request registry (request_id ->
        # (Request, replica idx)) so a replica death / unhealthy shed can
        # re-dispatch the actual Request object; entries drop as their
        # done events stream. Homogeneous-fleet knob like slo below.
        self._requests = {}
        self._retry_limit = int(replicas[0].cfg.retry_limit)
        self._chaos = []                          # (ReplicaEvent, ...) queue
        self._chaos_pos = 0
        # fleet SLO targets: the serving.slo block (homogeneous fleet — the
        # first replica's config speaks for all, like cfg.router above)
        self._slo = replicas[0].cfg.slo
        # disaggregated prefill/decode pools (serving.pools): the first
        # ``prefill_replicas`` indices prefill-to-first-token and hand off,
        # the rest decode — per-pool overrides applied per replica here
        # (the shared config object is never mutated)
        self._pools = replicas[0].cfg.pools
        self._pools_on = bool(self._pools.enabled)
        if self._pools_on:
            want = self._pools.prefill_replicas + self._pools.decode_replicas
            if want != len(self._replicas):
                raise ValueError(
                    f"serving.pools: prefill_replicas "
                    f"({self._pools.prefill_replicas}) + decode_replicas "
                    f"({self._pools.decode_replicas}) must equal the fleet "
                    f"size ({len(self._replicas)})")
            for rep in self._replicas:
                if rep.idx < self._pools.prefill_replicas:
                    rep.role = "prefill"
                    rep.sv.set_pool_role(
                        "prefill",
                        chunk_size=self._pools.prefill_chunk_size,
                        speculation=self._pools.prefill_speculation)
                else:
                    rep.role = "decode"
                    rep.sv.set_pool_role(
                        "decode",
                        chunk_size=self._pools.decode_chunk_size,
                        speculation=self._pools.decode_speculation)
        # live rebalancing (serving.rebalance): hysteresis-guarded actuator
        # over the migration machinery, evaluated on its own cadence
        self._rebalance_cfg = replicas[0].cfg.rebalance
        self._rebalance_calls = 0
        self._rebalance_next = 0.0   # cooldown gate (fleet-frontier time)
        # SLO-armed rebalance retarget: per-replica windowed burn sensors
        # (idx -> BurnSensor), consulted only when serving.slo is armed
        self._rebalance_sensors = {}
        self.metrics = RouterMetrics(self, monitor=monitor)
        self.tracer, self._fleet_dir = self._setup_tracing(tracer)
        self._rehome_replica_monitors()
        for rep in self._replicas:
            # per-replica snapshots gain the cross-replica view (coherent
            # with the Serving/router_* events, asserted tier-1)
            rep.sv.metrics.router = self.metrics.snapshot
        # SLO-driven autoscaling (serving.autoscaler): parks the fleet to
        # its floor NOW (drains are instant pre-traffic), then scales the
        # active set from the router loop — constructed last so the park
        # events land on live metrics/tracer state
        auto_cfg = replicas[0].cfg.autoscaler
        self._autoscaler = Autoscaler(self, auto_cfg) \
            if auto_cfg is not None and auto_cfg.enabled else None

    def _setup_tracing(self, tracer):
        """Arm fleet tracing when the replicas trace. Replicas built from
        one shared telemetry config all point at the SAME output dir (their
        flushes would clobber each other) — re-home each to
        ``<base>/replica<i>``, put the router's own decision stream at
        ``<base>/router``, and reserve ``<base>`` itself for the MERGED
        fleet files (trace.json / spans.jsonl / requests.jsonl /
        fleet.json, written by ``write_fleet_trace``). Replicas the caller
        pointed at DISTINCT dirs are deliberate — leave them untouched and
        skip the automatic fleet write (``write_fleet_trace(output_dir)``
        still merges on demand)."""
        from ..telemetry import SpanTracer

        dirs = [r.sv.tracer.output_dir for r in self._replicas
                if r.sv.tracer.enabled and r.sv.tracer.output_dir]
        # the fleet base (merged files + auto write) requires the common
        # shared-config case: every enabled tracer on ONE dir. Mixed
        # configs still get COLLIDING groups re-homed (same-path flushes
        # truncate each other) — just no automatic fleet dir.
        base = dirs[0] if dirs and len(set(dirs)) == 1 else None
        by_dir = {}
        for i, rep in enumerate(self._replicas):
            t = rep.sv.tracer
            if t.enabled and t.output_dir:
                by_dir.setdefault(t.output_dir, []).append((i, rep))
        for d, group in by_dir.items():
            if len(group) < 2 and base is None:
                continue  # unique dir in a mixed config: deliberate
            for i, rep in group:
                rep.sv.tracer.output_dir = os.path.join(d, f"replica{i}")
        if tracer is None:
            # the router's clock is the fleet frontier: route decisions
            # happen at the newest clock any replica has reached
            tracer = SpanTracer(
                enabled=bool(dirs), clock=self._frontier,
                output_path=base or "", job_name="router",
                chrome_trace=False, meta={"process": "router"})
        return tracer, base

    def _frontier(self):
        return max(r.sv.clock.now() for r in self._replicas)

    def _rehome_replica_monitors(self):
        """N replicas auto-built from ONE shared engine config each carry
        their own MonitorMaster over the SAME file paths: their Serving/*
        series would interleave in one CSV / scalars.jsonl with duplicate
        step counters. Re-home colliding file-backed backends to
        ``<path>/replica<i>`` (mirroring the tracer re-homing); writer-
        holding backends (TensorBoard/W&B) cannot be re-pointed — warn
        once. Distinct monitor OBJECTS only: a single master deliberately
        shared across replicas is left alone."""
        from ..monitor.monitor import CSVMonitor, TraceFileMonitor
        from ..utils.logging import logger

        by_path = {}
        unmovable = collections.Counter()
        for i, rep in enumerate(self._replicas):
            m = rep.sv.metrics.monitor
            for b in getattr(m, "backends", []):
                if not b.enabled:
                    continue
                if isinstance(b, CSVMonitor) and b.output_path:
                    by_path.setdefault(("csv", b.output_path), {})[id(b)] = \
                        (i, b)
                elif isinstance(b, TraceFileMonitor) and b.path:
                    by_path.setdefault(("scalars", b.path), {})[id(b)] = \
                        (i, b)
                elif type(b).__name__ in ("TensorBoardMonitor",
                                          "WandbMonitor"):
                    # writer-holding backends can't be re-pointed; a real
                    # collision means replicas share ONE engine config
                    # (deliberately-distinct configs don't warn)
                    unmovable[(type(b).__name__,
                               id(rep.sv.engine.config))] += 1
        for (kind, path), items in by_path.items():
            if len(items) < 2:
                continue
            for i, b in items.values():
                if kind == "csv":
                    b.output_path = os.path.join(path, f"replica{i}")
                    os.makedirs(b.output_path, exist_ok=True)
                else:
                    d = os.path.join(os.path.dirname(path), f"replica{i}")
                    os.makedirs(d, exist_ok=True)
                    b.path = os.path.join(d, "scalars.jsonl")
                    # fresh run, fresh stream (write_events appends): a
                    # rerun into the same dir must not concatenate two
                    # runs' series — TraceFileMonitor.__init__ truncates
                    # its original path for exactly this reason
                    open(b.path, "w").close()
        shared = max(unmovable.values(), default=0)
        if shared > 1:
            logger.warning(
                "Router: %d replicas write TensorBoard/W&B streams from one "
                "shared config; their Serving/* series will interleave — "
                "give replicas distinct job names or monitor at the router "
                "only", shared)

    # ------------------------------------------------------------- dispatch
    def submit(self, request):
        """Route one request to a replica (or shed it router-side).

        Returns the Request; ``state is REJECTED`` with ``reject_reason ==
        'all_replicas_saturated'`` means no live replica had queue room —
        the cross-replica generalization of ``queue_full``. Request-
        intrinsic sheds (``prompt_too_long``, ``no_free_blocks``) propagate
        from the chosen replica unchanged: a homogeneous fleet would shed
        them everywhere, so there is nothing to retry."""
        req = as_request(request)
        if req.request_id is None:
            # router-global ids: replicas must not hand out colliding ones
            req.request_id = self._next_id
            self._next_id += 1
        if req.trace_id is None:
            # fleet-global trace id: every span/instant on every replica
            # inherits it, so the merger stitches one cross-replica journey
            req.trace_id = f"req-{req.request_id:06d}"
        now = req.arrival_time if req.arrival_resolved else self._frontier()
        live = [i for i, r in enumerate(self._replicas)
                if not r.draining and not r.saturated and not r.dead]
        if not live:
            req.state = RequestState.REJECTED
            req.reject_reason = REJECT_ALL_REPLICAS_SATURATED
            self.metrics.shed_saturated += 1
            self.tracer.instant("route/shed", cat="router", ts=now,
                                request_id=req.request_id,
                                trace_id=req.trace_id,
                                reason=REJECT_ALL_REPLICAS_SATURATED)
            return req
        idx, decision = self._route(req, live)
        # the route/decision instant: full score breakdown + why this
        # replica — the wide event's "routing" block, recorded BEFORE the
        # replica touches the request so a replica-side shed still has it
        self.tracer.instant("route/decision", cat="router", ts=now,
                            request_id=req.request_id,
                            trace_id=req.trace_id, replica=idx, **decision)
        self._replicas[idx].sv.submit(req)
        if req.state is RequestState.REJECTED:
            # request-intrinsic shed (prompt_too_long / no_free_blocks):
            # not routed work — and registering its prefix/session would
            # build affinity toward blocks that never materialized
            return req
        self.metrics.routed += 1
        self.metrics.per_replica_routed[idx] += 1
        self._requests[req.request_id] = (req, idx)
        if req.session_id is not None and self.cfg.session_affinity:
            self._sessions[req.session_id] = idx
        self._register_prefix(req, idx)
        return req

    def _route(self, req, live):
        """Pick a replica index from ``live``: affinity target if healthy,
        else the load-policy choice (overriding affinity = a rebalance).
        Returns ``(index, decision)`` — the decision dict is the
        ``route/decision`` instant's score breakdown (per-replica load
        scores, affinity kind honored, rebalance flag), i.e. WHY this
        replica, postmortem-readable."""
        scores = {i: self._replicas[i].load_score(self.cfg) for i in live}
        # disaggregated pools: FRESH work dispatches into the prefill pool
        # (scored on queue depth + pending prompt tokens); affinity may
        # still pull it to ANY live replica — a decode-side prefix hit
        # routes there directly (suffix-only prefill, no handoff needed).
        # An all-dead/draining prefill pool degrades to the whole fleet.
        if self._pools_on:
            cands = [i for i in live
                     if self._replicas[i].role == "prefill"] or live
            pool_scores = {i: self._replicas[i].pool_score(self.cfg)
                           for i in cands}
        else:
            cands, pool_scores = live, scores
        decision = {"policy": self.cfg.policy,
                    "scores": {str(i): round(s, 6)
                               for i, s in scores.items()},
                    "affinity": None, "rebalanced": False}
        if self._pools_on:
            decision["pool_scores"] = {str(i): round(s, 6)
                                       for i, s in pool_scores.items()}
        if self.cfg.policy == "round_robin":
            # round_robin ignores load AND affinity (no lookups, no hit
            # counting) — it is the baseline the affinity/load policies are
            # measured against. Under pools it cycles the prefill pool.
            for _ in range(len(self._replicas)):
                cand = self._rr_next % len(self._replicas)
                self._rr_next += 1
                if cand in pool_scores:
                    self._note_pool(decision, cand)
                    return cand, decision
            self._note_pool(decision, cands[0])
            return cands[0], decision
        target = kind = None
        if self.cfg.session_affinity and req.session_id is not None:
            t = self._sessions.get(req.session_id)
            if t in scores:
                target, kind = t, "session"
        if target is None and self.cfg.prefix_affinity:
            target = self._prefix_lookup(req, scores)
            kind = "prefix" if target is not None else None
        best = min(cands, key=lambda i: (pool_scores[i], i))
        if target is not None:
            if scores[target] - scores[best] <= self.cfg.rebalance_margin:
                # hits count ONLY when the affinity target is actually used:
                # affinity_hit_rate means "routed by affinity", and a
                # rebalanced-away lookup must not inflate it
                if kind == "session":
                    self.metrics.session_hits += 1
                else:
                    self.metrics.prefix_hits += 1
                decision["affinity"] = kind
                self._note_pool(decision, target)
                return target, decision
            # affinity would pile onto an overloaded replica: rebalance
            self.metrics.rebalances += 1
            decision["rebalanced"] = True
            decision["affinity_overridden"] = kind
        self._note_pool(decision, best)
        return best, decision

    def _note_pool(self, decision, idx):
        if self._pools_on:
            decision["pool"] = self._replicas[idx].role

    def _prefix_lookup(self, req, scores):
        """Longest prefix-chain-key hit among live replicas (the paged
        pool's SHA-256 chain keys as the cross-replica currency)."""
        bs = self._chain_block_size()
        if bs is None or req.prompt_len <= bs:
            return None
        self.metrics.prefix_lookups += 1
        # longest-first: the deepest cached prefix wins (its replica saves
        # the most prefill). The hit counter moves in _route — a target
        # rebalanced away for load is a lookup, not a hit.
        keys = prefix_chain_keys(req.prompt, bs, req.prompt_len - 1)
        for key, _end in reversed(keys):
            idx = self._prefix_index.get(key)
            if idx is not None and idx in scores:
                self._prefix_index.move_to_end(key)
                return idx
        return None

    def _register_prefix(self, req, idx):
        """Record the request's full prompt blocks as living on ``idx``
        (last-writer-wins; bounded LRU)."""
        bs = self._chain_block_size()
        if bs is None or not self.cfg.prefix_affinity:
            return
        for key, _end in prefix_chain_keys(req.prompt, bs,
                                           req.prompt_len - 1):
            self._prefix_index[key] = idx
            self._prefix_index.move_to_end(key)
        while len(self._prefix_index) > self.cfg.prefix_index_cap:
            self._prefix_index.popitem(last=False)

    def _chain_block_size(self):
        """The chain-key granularity: the first paged replica's block size
        (None when no replica pages — there are no blocks to share)."""
        for r in self._replicas:
            if r.sv.paged and r.sv.cfg.kv_pool.prefix_cache:
                return r.sv.pool_mgr.block_size
        return None

    # ------------------------------------------------------ drain / rejoin
    def drain(self, idx, migrate=False):
        """Stop routing new work to replica ``idx``.

        ``migrate=False`` (wait-for-finish): in-flight requests keep
        decoding to completion (``drained(idx)`` turns True) — the safe
        moment to ``sv.destroy()`` for a restart. ``migrate=True``
        (drain-by-migration): every in-flight stream is captured as a
        FRESH snapshot and live-moved to a peer replica instead, so the
        replica empties after ONE evacuation pass and its restart loses
        zero computed tokens (a fresh snapshot splices with zero
        recompute). Voluntary moves never burn the retry budget. Returns
        the shed TokenEvents the evacuation produced (normally empty)."""
        rep = self._replicas[idx]
        if not rep.draining:
            rep.draining = True
            self.metrics.drains += 1
        if not migrate or rep.dead:
            return []
        moved = rep.sv.evacuate()
        started = [r for r in moved if r.tokens
                   or r.prefill_start_time is not None]
        started_ids = {id(r) for r in started}
        queued = [r for r in moved if id(r) not in started_ids]
        events = []
        # started streams land at their target's queue head — dispatch in
        # REVERSE seniority so successive push_fronts leave the most
        # senior request at the head
        for req in reversed(started):
            events.extend(self._failover(req, idx, "drain",
                                         count_retry=False))
        for req in queued:
            events.extend(self._failover(req, idx, "drain",
                                         count_retry=False))
        return events

    def kill_replica(self, idx):
        """Seeded fault surface: replica ``idx`` crashes NOW. Its device
        state is gone — no capture, no release — so affected requests fail
        over to survivors from their last periodic snapshot (splice + tail
        replay) or, with no snapshot, replay prompt + committed tokens as
        a chunkable resume prefill (counted as replay tokens in goodput).
        Each started re-dispatch burns one unit of the bounded retry
        budget (``serving.retry_limit``); the terminal fallback is a
        shed-with-reason ``replica_failed``. The dead replica's affinity
        state is purged so nothing routes toward vanished blocks. Returns
        the TokenEvents (terminal sheds) the failover produced."""
        rep = self._replicas[idx]
        if rep.dead:
            return []
        rep.health = "dead"
        rep.draining = True
        self.metrics.replica_kills += 1
        self.tracer.instant("replica/killed", cat="router",
                            ts=self._frontier(), replica=idx,
                            inflight=len(rep.sv._slots)
                            + len(rep.sv._prefill_jobs)
                            + rep.sv.queue.depth)
        for key in [k for k, v in self._prefix_index.items() if v == idx]:
            del self._prefix_index[key]
        for sid in [s for s, v in self._sessions.items() if v == idx]:
            del self._sessions[sid]
        affected = rep.sv.abandon_inflight()
        started = [r for r in affected if r.tokens
                   or r.prefill_start_time is not None]
        started_ids = {id(r) for r in started}
        queued = [r for r in affected if id(r) not in started_ids]
        events = []
        for req in reversed(started):
            events.extend(self._failover(req, idx, "replica_killed"))
        for req in queued:
            events.extend(self._failover(req, idx, "replica_killed"))
        return events

    def stall_replica(self, idx, duration):
        """Seeded fault surface: replica ``idx`` freezes for ``duration``
        seconds (a GC pause / preemptible-host interruption). Its clock
        jumps forward, so the conservative DES starves it until the rest
        of the fleet catches up — every co-resident request eats the
        latency, no state is lost. Health reads ``degraded`` until the
        fleet frontier passes the stall."""
        rep = self._replicas[idx]
        if rep.dead:
            return
        rep.sv.clock.sleep(float(duration))
        rep.stall_until = rep.sv.clock.now()
        rep.health = "degraded"
        self.metrics.replica_stalls += 1
        self.tracer.instant("replica/stalled", cat="router",
                            ts=self._frontier(), replica=idx,
                            duration=float(duration))

    def _update_health(self):
        """Degraded -> live once every surviving clock passed the stall."""
        alive = [r.sv.clock.now() for r in self._replicas if not r.dead]
        if not alive:
            return
        floor = min(alive)
        for rep in self._replicas:
            if rep.health == "degraded" and floor >= rep.stall_until:
                rep.health = "live"

    def _failover(self, req, from_idx, why, count_retry=True):
        """Re-dispatch one request off a dead (or migrating) replica.

        STARTED requests (committed tokens / prefill begun) are the
        expensive case: each involuntary move counts against
        ``serving.retry_limit`` (``count_retry``), the resume rng is
        re-derived (snapshot chain advanced host-side, or the insert-time
        chain key re-derived when no snapshot exists), and the request
        lands at the least-loaded survivor's QUEUE HEAD — committed
        tokens outrank queued arrivals, and ``push_front`` deliberately
        bypasses depth bounds. Queued-only requests re-route free through
        normal admission. Never goes through ``submit()``: that would
        reset ``submit_time`` and double-count ``record_submit``."""
        started = bool(req.tokens) or req.prefill_start_time is not None
        if started and count_retry:
            req.failovers += 1
            if req.failovers > self._retry_limit:
                return self._shed_failed(req, from_idx, "retry_limit")
        live = [i for i, r in enumerate(self._replicas)
                if r.health != "dead" and not r.draining]
        if not live:
            return self._shed_failed(req, from_idx, "no_live_replica")
        scores = {i: self._replicas[i].load_score(self.cfg) for i in live}
        if started:
            # disaggregated pools: a started stream is decode work — it
            # recovers into the decode pool (any survivor when none lives)
            target = min(self._pool_candidates(live, "decode"),
                         key=lambda i: (scores[i], i))
            sv = self._replicas[target].sv
            snap = req.migration
            if req.tokens:
                if snap is not None and len(req.tokens) >= len(snap.tokens):
                    # re-join the original rng chain at the current commit
                    # point: the tokens since the capture replay as
                    # teacher-forced prefill
                    req.resume_rng = advance_rng(
                        snap.rng, len(req.tokens) - len(snap.tokens))
                elif req.resume_rng is None:
                    req.resume_rng = sv.chain_key_for_resume(req)
            req.slot = None
            req.state = RequestState.QUEUED
            req.reject_reason = None
            req.finish_reason = None
            sv.queue.push_front(req)
            if count_retry:
                self.metrics.failovers += 1
        else:
            candidates = [i for i in live
                          if not self._replicas[i].saturated]
            if not candidates:
                return self._shed_failed(req, from_idx, "all_saturated")
            # a queued request still owes its whole prefill: prefill pool
            target = min(self._pool_candidates(candidates, "prefill"),
                         key=lambda i: (scores[i], i))
            sv = self._replicas[target].sv
            reason = sv.queue.admit(
                req, sv.max_len,
                kv_fits=sv.pool_mgr.fits_ever if sv.paged else None)
            if reason is not None:
                return self._shed_failed(req, from_idx, reason)
        self._requests[req.request_id] = (req, target)
        self.tracer.instant("route/failover", cat="router",
                            ts=self._frontier(), request_id=req.request_id,
                            trace_id=req.trace_id, replica=from_idx,
                            target=target, why=why, started=started,
                            n_tokens=len(req.tokens),
                            snapshot=req.migration is not None,
                            failovers=req.failovers)
        return []

    def _shed_failed(self, req, from_idx, why):
        """Terminal failover fallback: shed with reason ``replica_failed``
        (budget spent / no survivor with room). Router-side count, like
        ``all_replicas_saturated``."""
        req.state = RequestState.REJECTED
        req.reject_reason = REJECT_REPLICA_FAILED
        req.finish_reason = None
        req.slot = None
        self.metrics.shed_replica_failed += 1
        self._requests.pop(req.request_id, None)
        now = self._frontier()
        self.tracer.instant("route/shed", cat="router", ts=now,
                            request_id=req.request_id,
                            trace_id=req.trace_id,
                            reason=REJECT_REPLICA_FAILED, detail=why,
                            replica=from_idx)
        return [TokenEvent(req.request_id, -1, len(req.tokens), True,
                           f"rejected:{REJECT_REPLICA_FAILED}", now)]

    def _retry_unhealthy(self, req, from_idx):
        """Satellite of the failover machinery: an ``unhealthy_slot`` shed
        on a multi-replica fleet retries ONCE (bounded by
        ``serving.retry_limit``) on a DIFFERENT replica before the shed
        becomes terminal — the poisoned prefill fired before the first
        token streamed, so nothing user-visible rewinds. Returns True
        (event swallowed, fleet will finish the request), a list of
        terminal shed events, or None (no candidate: the original
        unhealthy event stands)."""
        live = [i for i, r in enumerate(self._replicas)
                if i != from_idx and r.health == "live" and not r.draining
                and not r.saturated]
        if not live:
            return None
        req.reset_for_retry()
        req.retries += 1
        self.metrics.retries += 1
        scores = {i: self._replicas[i].load_score(self.cfg) for i in live}
        # the poisoned prefill never streamed a token: it is prefill work
        target = min(self._pool_candidates(live, "prefill"),
                     key=lambda i: (scores[i], i))
        sv = self._replicas[target].sv
        reason = sv.queue.admit(
            req, sv.max_len,
            kv_fits=sv.pool_mgr.fits_ever if sv.paged else None)
        if reason is not None:
            return self._shed_failed(req, from_idx, reason)
        self._requests[req.request_id] = (req, target)
        self.tracer.instant("route/retry", cat="router",
                            ts=self._frontier(), request_id=req.request_id,
                            trace_id=req.trace_id,
                            reason=FINISH_UNHEALTHY, replica=from_idx,
                            target=target, retries=req.retries)
        return True

    def _pool_candidates(self, live, role):
        """Restrict ``live`` to the given pool under disaggregation; the
        whole list when pools are off or the pool has no live member (a
        decode-pool wipeout degrades to mixed service, never to an outage)."""
        if not self._pools_on:
            return live
        return [i for i in live if self._replicas[i].role == role] or live

    # -------------------------------------------- first-token handoff
    def _handoff(self, req, from_idx):
        """Move a stream that just committed its FIRST token off its
        prefill replica into the decode pool: capture a fresh snapshot
        (partial tail block included — zero recompute on splice, and
        delta-to-capture is 0 so the rng chain passes through unchanged:
        the decode replica's stream is bitwise the prefill replica's
        continuation), free the prefill slot (it re-admits the next prompt
        immediately — the TTFT win), and queue-head the request at the
        least-occupied decode replica. A handoff failure is not terminal:
        with no live decode replica the stream simply keeps decoding where
        it is, and a target that dies mid-splice recovers through the
        normal failover path (the request carries the snapshot)."""
        decode = [i for i, r in enumerate(self._replicas)
                  if r.role == "decode" and r.health != "dead"
                  and not r.draining]
        if not decode:
            return False
        target = min(decode,
                     key=lambda i: (self._replicas[i].decode_score(self.cfg),
                                    i))
        rep = self._replicas[from_idx]
        if not rep.sv.evacuate_request(req, instant="request/handoff_out"):
            return False
        req.handoff_pending = True
        now = rep.sv.clock.now()
        self._push_started(req, target, now)
        # the decode replica now owns the stream's blocks: future
        # identical prompts route straight to it (cross-pool dedupe —
        # prefix affinity both directions)
        self._register_prefix(req, target)
        self.metrics.handoffs += 1
        self.tracer.instant("route/handoff", cat="router", ts=now,
                            request_id=req.request_id,
                            trace_id=req.trace_id, replica=from_idx,
                            target=target, n_tokens=len(req.tokens))
        return True

    def _push_started(self, req, target, now):
        """Land a moved started stream at ``target``'s queue head.
        Causality under the DES: an IDLE target's clock may lag the move
        instant — idle time passes before the splice can land (a busy
        target's skew is already bounded by the laggard-first stepping)."""
        rep = self._replicas[target]
        if not rep.busy:
            gap = now - rep.sv.clock.now()
            if gap > 0:
                rep.sv.clock.sleep(gap)
        rep.sv.queue.push_front(req)
        self._requests[req.request_id] = (req, target)

    # -------------------------------------------------- live rebalancing
    def _move_delta(self, hot, cold, req):
        """Predicted total score shift of moving ``req`` hot -> cold: the
        slot term leaves one side and lands on the other, and the stream's
        blocks migrate between the pools. The overshoot guard compares the
        measured gap against this BEFORE moving — the units are the same
        (both are load-score points), so the comparison is exact up to
        on-demand pool growth."""
        d = self.cfg.slot_weight * (1.0 / max(hot.sv.n_slots, 1)
                                    + 1.0 / max(cold.sv.n_slots, 1))
        if hot.sv.paged and cold.sv.paged:
            blocks = -(-(req.prompt_len + len(req.tokens))
                       // hot.sv.pool_mgr.block_size)
            d += self.cfg.block_weight * blocks * (
                1.0 / max(hot.sv.pool_mgr.n_blocks, 1)
                + 1.0 / max(cold.sv.pool_mgr.n_blocks, 1))
        return d

    def _maybe_rebalance(self):
        """The bounded, hysteresis-guarded rebalance trigger (serving.
        rebalance): when the hottest decode replica's score exceeds the
        coldest's by more than ``min_gain``, migrate up to
        ``max_concurrent`` longest-tail streams hot -> cold, then cool
        down. Thrash-proof by construction: a stream moves only when the
        measured gap ALSO exceeds its predicted score shift minus
        ``min_gain`` (the overshoot guard — the post-move REVERSE gap
        ``delta - gap`` stays strictly inside the hysteresis band, so the
        move itself can never arm the opposite trigger; only an external
        load change can), moves stop the moment the RE-MEASURED gap falls
        inside the band, every trigger is followed by a ``cooldown``
        window, and voluntary moves never burn the retry budget."""
        cfg = self._rebalance_cfg
        if not cfg.enabled:
            return
        self._rebalance_calls += 1
        if self._rebalance_calls % cfg.interval:
            return
        now = self._frontier()
        if now < self._rebalance_next:
            return
        cands = [r for r in self._replicas
                 if r.health == "live" and not r.draining
                 and (not self._pools_on or r.role == "decode")]
        if len(cands) < 2:
            return
        score = lambda r: r.decode_score(self.cfg)
        if self._slo is not None and self._slo.armed:
            # SLO-armed retarget: hot/cold selection scores each replica
            # by its WINDOWED burn contribution (the latency damage it is
            # doing to the fleet SLO right now), decode occupancy only
            # breaking ties — a replica can sit at modest occupancy yet
            # burn the budget (long-tail streams), and it is the one worth
            # unloading. A move still requires a strictly positive
            # occupancy gap toward the cold replica and passes the same
            # per-stream overshoot guard below, so the no-thrash argument
            # carries over: the guard bounds every move's reverse gap
            # inside the hysteresis band regardless of how hot/cold were
            # chosen, and burn windows re-baseline per evaluation.
            targets = self._slo.targets_ms()
            burns = {}
            for r in cands:
                sensor = self._rebalance_sensors.setdefault(
                    r.idx, BurnSensor())
                burns[r.idx] = sensor.update(
                    targets, r.sv.metrics.latency_digests())
            hot = max(cands, key=lambda r: (burns[r.idx], score(r), r.idx))
            cold = min(cands, key=lambda r: (burns[r.idx], score(r), r.idx))
            if hot is cold or burns[hot.idx] <= burns[cold.idx]:
                return  # no burn differential: nothing to unload
            gap_floor = 0.0   # burn triggered the move; any headroom helps
            if score(hot) - score(cold) <= gap_floor:
                return  # the cold replica has no spare capacity to absorb
        else:
            gap_floor = cfg.min_gain
            hot = max(cands, key=lambda r: (score(r), r.idx))
            cold = min(cands, key=lambda r: (score(r), r.idx))
            if hot is cold or score(hot) - score(cold) <= cfg.min_gain:
                return
        # longest-tail first: the streams with the most decode left
        # amortize the splice cost best (and vacate the most future work)
        streams = sorted(
            (r for r in hot.sv._slots.values() if r.tokens),
            key=lambda r: r.max_new_tokens - len(r.tokens), reverse=True)
        moved = 0
        for req in streams:
            gap = score(hot) - score(cold)
            if moved >= cfg.max_concurrent or gap <= gap_floor:
                break
            if gap <= self._move_delta(hot, cold, req) - cfg.min_gain:
                # overshoot guard: this stream is heavy enough that moving
                # it would swing the pair past equality by more than the
                # hysteresis band and re-trigger in reverse — a lighter
                # stream further down the tail may still fit
                continue
            if not hot.sv.evacuate_request(req):
                continue
            req.rebalances += 1
            self._push_started(req, cold.idx, now)
            self._register_prefix(req, cold.idx)
            self.metrics.pool_rebalances += 1
            self.tracer.instant("route/rebalance", cat="router", ts=now,
                                request_id=req.request_id,
                                trace_id=req.trace_id, replica=hot.idx,
                                target=cold.idx, n_tokens=len(req.tokens),
                                remaining=req.max_new_tokens
                                - len(req.tokens))
            moved += 1
        if moved:
            self._rebalance_next = now + cfg.cooldown

    def _filter_events(self, idx, raw):
        """Every replica step's events pass through here: unhealthy_slot
        sheds get the cross-replica retry (swallowed on success — the
        consumer never sees a request fail that the fleet then finishes),
        a prefill replica's FIRST-token events trigger the prefill->decode
        handoff, and finished requests leave the in-flight registry."""
        out = []
        prefill_side = self._pools_on \
            and self._replicas[idx].role == "prefill"
        for ev in raw:
            if ev.finish_reason == FINISH_UNHEALTHY:
                entry = self._requests.get(ev.request_id)
                req = entry[0] if entry is not None else None
                if req is not None and not req.tokens \
                        and req.retries < self._retry_limit:
                    res = self._retry_unhealthy(req, idx)
                    if res is True:
                        continue
                    if res is not None:
                        out.extend(res)
                        continue
            if prefill_side and not ev.done and ev.index == 0:
                # first token committed on the prefill side: hand the
                # stream off (the event itself still streams — the token
                # is committed; only the REST of the decode moves)
                entry = self._requests.get(ev.request_id)
                if entry is not None and entry[1] == idx:
                    self._handoff(entry[0], idx)
            if ev.done:
                self._requests.pop(ev.request_id, None)
            out.append(ev)
        return out

    # ------------------------------------------------------- chaos schedule
    def apply_chaos(self, schedule):
        """Arm a seeded replica-level fault schedule
        (``testing.fault_injection.ReplicaChaosSchedule`` or any iterable
        of ``(time, kind, replica, duration)``): events fire inside the
        serve/step loops when the fleet frontier reaches their instant —
        same seed, same schedule, same recovery, deterministically."""
        events = getattr(schedule, "events", schedule)
        self._chaos = sorted(tuple(e) for e in events)
        self._chaos_pos = 0

    def _fire_chaos(self):
        """Fire every armed fault whose instant the frontier has reached;
        returns the terminal shed TokenEvents the failovers produced."""
        out = []
        while self._chaos_pos < len(self._chaos):
            t, kind, idx, duration = self._chaos[self._chaos_pos]
            if self._frontier() < t:
                break
            self._chaos_pos += 1
            if self._replicas[idx].dead:
                continue
            if kind == "kill":
                out.extend(self.kill_replica(idx))
            elif kind == "stall":
                self.stall_replica(idx, duration)
        return out

    def pull_queued(self, from_idx, to_idx, n):
        """Move up to ``n`` not-yet-started requests from the TAIL of
        replica ``from_idx``'s queue onto replica ``to_idx`` (relative
        order preserved). The autoscaler's scale-up companion: queued
        requests were routed before the new capacity existed — without the
        pull a rejoined standby idles while the hot queue drains one
        prefill per step. Tail-side so preemption returners and senior
        arrivals keep their position; admission control is bypassed like
        ``push_front`` (the requests already passed it at submit). Returns
        the number of requests moved."""
        src = self._replicas[from_idx].sv
        dst_rep = self._replicas[to_idx]
        moved = []
        for _ in range(max(int(n), 0)):
            if not len(src.queue) or src.queue.peek_at(
                    len(src.queue) - 1).admit_time is not None:
                break  # never pull a preemption returner off its replica
            moved.append(src.queue.pop_at(len(src.queue) - 1))
        if not moved:
            return 0
        now = self._frontier()
        # an idle target's clock may lag the move (cf. _push_started)
        if not dst_rep.busy:
            gap = now - dst_rep.sv.clock.now()
            if gap > 0:
                dst_rep.sv.clock.sleep(gap)
        for req in reversed(moved):   # popped back-to-front: re-append in order
            dst_rep.sv.queue._q.append(req)
            self._requests[req.request_id] = (req, to_idx)
        self.tracer.instant("route/pull_queued", cat="router", ts=now,
                            replica=from_idx, target=to_idx,
                            moved=len(moved))
        return len(moved)

    def drained(self, idx):
        """True once the draining replica has no in-flight work left."""
        return not self._replicas[idx].busy

    def rejoin(self, idx, engine=None):
        """Re-admit replica ``idx``. ``engine``: a replacement ServingEngine
        after a restart — its pool is empty, so the router purges the
        replica's prefix-index entries and session stickiness (stale
        affinity would route cache misses at it)."""
        rep = self._replicas[idx]
        if engine is not None:
            rep.sv = engine
            engine.metrics.router = self.metrics.snapshot
            for key in [k for k, v in self._prefix_index.items() if v == idx]:
                del self._prefix_index[key]
            for sid in [s for s, v in self._sessions.items() if v == idx]:
                del self._sessions[sid]
        elif rep.dead:
            raise ValueError(
                f"rejoin({idx}): a killed replica's device state is gone — "
                "pass a replacement engine")
        rep.draining = False
        rep.health = "live"
        rep.stall_until = 0.0
        self.metrics.rejoins += 1

    # ------------------------------------------------------------- the loop
    def step(self):
        """One scheduler step on every busy replica (the wall-clock /
        manual-driving path). Returns the concatenated TokenEvents."""
        events = list(self._fire_chaos())
        self._update_health()
        self._maybe_rebalance()
        if self._autoscaler is not None:
            self._autoscaler.maybe_scale()
        for rep in self._replicas:
            if rep.busy and not rep.dead:
                self.metrics.replica_steps += 1
                events.extend(self._filter_events(rep.idx, rep.sv.step()))
        self.metrics.maybe_emit()
        return events

    def serve(self, requests=None, yield_rejections=True):
        """Streaming frontend over the fleet: feed ``requests`` (each
        optionally carrying an ``arrival_time`` offset) through the router,
        yielding TokenEvents as replicas produce them.

        Under virtual clocks this is a conservative discrete-event
        simulation of N PARALLEL replicas: each replica advances its own
        clock by its own work, and the router always steps the busy replica
        whose local clock is furthest behind, dispatching arrivals due by
        that horizon first. Makespan is ``max`` over replica clocks, not the
        sum — which is what makes least-loaded measurably beat round-robin
        in tier-1. With wall clocks every busy replica steps each loop."""
        pending = sorted((as_request(r) for r in (requests or [])),
                         key=lambda r: r.arrival_time or 0.0)
        virtual = all(isinstance(r.sv.clock, VirtualClock)
                      for r in self._replicas)
        t0 = max(r.sv.clock.now() for r in self._replicas)
        for r in pending:
            if not r.arrival_resolved:
                r.arrival_time = t0 + (r.arrival_time or 0.0)
                r.arrival_resolved = True
            elif r.arrival_time is None:
                r.arrival_time = t0
        try:
            while pending or any(r.busy and not r.dead
                                 for r in self._replicas):
                # armed faults fire at the frontier BEFORE new work lands:
                # a killed replica's failovers re-dispatch first, so this
                # round's routing already sees the shrunken fleet
                for ev in self._fire_chaos():
                    yield ev
                self._update_health()
                self._maybe_rebalance()
                if self._autoscaler is not None:
                    self._autoscaler.maybe_scale()
                busy = [r for r in self._replicas if r.busy and not r.dead]
                if busy:
                    horizon = min(r.sv.clock.now() for r in busy)
                else:
                    horizon = pending[0].arrival_time if pending else None
                while pending and horizon is not None \
                        and pending[0].arrival_time <= horizon:
                    for ev in self._dispatch(pending.pop(0),
                                             yield_rejections):
                        yield ev
                    busy = [r for r in self._replicas
                            if r.busy and not r.dead]
                if not busy:
                    if not pending:
                        break
                    # everyone idle: jump to the next arrival
                    self._catch_up_all(pending[0].arrival_time)
                    continue
                if virtual:
                    # advance the laggard one step: no replica's clock ever
                    # runs ahead of another's un-simulated past
                    rep = min(busy, key=lambda r: r.sv.clock.now())
                    self.metrics.replica_steps += 1
                    for ev in self._filter_events(rep.idx, rep.sv.step()):
                        yield ev
                else:
                    for rep in busy:
                        self.metrics.replica_steps += 1
                        for ev in self._filter_events(rep.idx,
                                                      rep.sv.step()):
                            yield ev
                self.metrics.maybe_emit()
        finally:
            # serve() completing (or dying) is the fleet's terminal edge:
            # flush EVERY tracer (replica tail spans would otherwise only
            # land at destroy()) and force one final metrics interval —
            # the rate-limited maybe_emit cadence must not swallow a short
            # run's only (or last) window of events
            for rep in self._replicas:
                rep.sv.tracer.flush()
                rep.sv.metrics.emit_events()
            self.metrics.emit_events()
            self.tracer.flush()
            if self._fleet_dir is not None:
                self.write_fleet_trace()

    def _dispatch(self, req, yield_rejections):
        # an idle target's clock may lag the arrival: idle time passes
        req = as_request(req)
        self._catch_up_idle(req.arrival_time)
        routed = self.submit(req)
        if routed.state is RequestState.REJECTED and yield_rejections:
            now = req.arrival_time if req.arrival_time is not None else 0.0
            return [TokenEvent(routed.request_id, -1, -1, True,
                               f"rejected:{routed.reject_reason}", now)]
        return []

    def _catch_up_idle(self, t):
        if t is None:
            return
        for rep in self._replicas:
            if not rep.busy:
                gap = t - rep.sv.clock.now()
                if gap > 0:
                    rep.sv.clock.sleep(gap)

    def _catch_up_all(self, t):
        for rep in self._replicas:
            gap = t - rep.sv.clock.now()
            if gap > 0:
                rep.sv.clock.sleep(gap)

    def run(self, requests):
        """Non-streaming convenience: serve to completion and return
        ``(finished, rejected, snapshot)`` (cf. ``ServingEngine.run``)."""
        reqs = [as_request(r) for r in (requests or [])]
        for _ in self.serve(reqs, yield_rejections=False):
            pass
        finished = [r for r in reqs if r.state is RequestState.FINISHED]
        rejected = [r for r in reqs if r.state is RequestState.REJECTED]
        return finished, rejected, self.snapshot()

    # -------------------------------------------------------------- rollups
    def snapshot(self):
        """Fleet rollup: the router block plus per-replica ServingMetrics
        snapshots and aggregate latency percentiles."""
        reps = [r.sv.metrics.snapshot() for r in self._replicas]
        ttft = [s for r in self._replicas
                for s in r.sv.metrics.ttft_samples]
        tpot = [s for r in self._replicas
                for s in r.sv.metrics.tpot_samples]
        to_ms = lambda v: None if v is None else v * 1e3
        digests = self.metrics.fleet_digests()
        return {
            "router": self.metrics.snapshot(),
            "replicas": reps,
            "finished": sum(r["finished"] for r in reps),
            "total_tokens": sum(r["total_tokens"] for r in reps),
            "ttft_ms": {"p50": to_ms(percentile(ttft, 50)),
                        "p99": to_ms(percentile(ttft, 99))},
            "tpot_ms": {"p50": to_ms(percentile(tpot, 50)),
                        "p99": to_ms(percentile(tpot, 99))},
            # fleet-merged streaming digests: percentile rollup + the raw
            # bucket snapshots (so fleet.json readers can rebuild and
            # compare digests exactly), the SLO grade, goodput accounting
            "percentiles": {name + "_ms": d.percentiles_ms()
                            for name, d in digests.items()},
            "digests": {name: d.snapshot() for name, d in digests.items()},
            "slo": self.metrics.fleet_slo(digests),
            "goodput": self.metrics.fleet_goodput(),
            # multi-tenant QoS: fleet-merged per-tenant counters/digests/
            # grades, plus the autoscaler's scale-event timeline (both
            # blocks always present so artifact readers need no probing)
            "tenancy": self.metrics.fleet_tenancy(),
            "autoscaler": self._autoscaler.snapshot()
            if self._autoscaler is not None else {"enabled": False},
            # >0 means the live digests were restarted mid-run (warmup
            # exclusion) and no longer cover the whole trace
            "window_resets": sum(r.sv.metrics.window_resets
                                 for r in self._replicas),
            "makespan": max(r.sv.clock.now() for r in self._replicas),
        }

    def write_fleet_trace(self, output_dir=None):
        """Merge the router + per-replica span streams into the fleet dir
        (``telemetry/fleet.py``): Chrome ``trace.json`` with one process
        lane per source, merged ``spans.jsonl``, per-request wide events
        (``requests.jsonl``) and the live ``fleet.json`` rollup. Defaults
        to the telemetry base dir the replicas were re-homed under."""
        out = output_dir if output_dir is not None else self._fleet_dir
        if out is None:
            raise ValueError(
                "no fleet output dir: enable telemetry on the replicas or "
                "pass output_dir")
        from ..telemetry.fleet import write_fleet_trace

        sources = [("router", self.tracer.events)]
        sources += [(f"replica{i}", rep.sv.tracer.events)
                    for i, rep in enumerate(self._replicas)]
        return write_fleet_trace(out, sources, fleet=self.snapshot())

    def compile_counts(self):
        return [r.sv.compile_counts() for r in self._replicas]

    def destroy(self):
        self.tracer.flush()
        for rep in self._replicas:
            rep.sv.destroy()
