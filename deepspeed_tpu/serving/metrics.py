"""Serving metrics: TTFT/TPOT histograms, throughput, occupancy, shed rate.

Mirrors the training engine's ``Comm/*_gb`` monitor pattern: the serving loop
records samples host-side and periodically writes ``Serving/*`` scalar events
through the existing ``monitor/`` fan-out (TensorBoard/W&B/CSV), gated on the
same monitor config sections. ``snapshot()`` is the machine-readable rollup
the load bench commits as its throughput–latency artifact.
"""

import collections

from .request import FINISH_UNHEALTHY


def percentile(samples, q):
    """Nearest-rank percentile (q in [0, 100]); None on no samples."""
    if not samples:
        return None
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


class ServingMetrics:
    def __init__(self, n_slots, clock, monitor=None, interval=32,
                 kv_pool=None):
        self.n_slots = n_slots
        self.clock = clock
        self.monitor = monitor
        self.interval = int(interval)
        # paged KV pool stats source (KVPoolManager.stats): block occupancy,
        # internal fragmentation, prefix hit rate — the memory-side truth
        # the slot-occupancy number no longer tells under paging
        self.kv_pool = kv_pool
        # router stats source (Router._router_stats, installed when this
        # replica registers with a Router): snapshot()["router"] then shows
        # the cross-replica view, coherent with the Serving/router_* events
        self.router = None
        self.start_time = clock.now()
        self._started = False       # start_time re-pins at first activity
        self._window_tokens = 0     # tokens since the last reset_window()
        self.total_tokens = 0
        self.submitted = 0
        self.finished = 0
        self.shed = collections.Counter()
        self.ttft_samples = []     # seconds (or virtual units)
        self.tpot_samples = []
        self.steps = 0
        self._queue_depth = 0
        self._active_slots = 0
        self.active_slots_peak = 0   # paged pool's ">= 2x effective slots" pin
        # numerics health (fed by the decode program's in-graph
        # nonfinite-logit count; see serving/engine.py _decode_once)
        self.nonfinite_logit_steps = 0  # decode steps with >=1 bad active slot
        self.unhealthy_slots = 0        # requests shed via unhealthy_slot
        # on-demand growth: requests preempted back to the queue on pool
        # exhaustion (they resume; NOT part of the shed/finished partition)
        self.preempted = 0

    # -- recording ----------------------------------------------------------
    def _mark_started(self):
        # the throughput window opens at the FIRST request, not at engine
        # construction — a server idle for an hour must not dilute tokens/s
        if not self._started:
            self.start_time = self.clock.now()
            self._started = True

    def reset_window(self):
        """Re-open the throughput window (e.g. after a warmup run): tokens/s
        reflects tokens since this call. Cumulative counters are kept."""
        self.start_time = self.clock.now()
        self._started = True
        self._window_tokens = 0

    def record_submit(self):
        self._mark_started()
        self.submitted += 1

    def record_shed(self, reason):
        self._mark_started()
        self.shed[reason] += 1

    def record_tokens(self, n):
        self.total_tokens += int(n)
        self._window_tokens += int(n)

    def record_first_token(self, request):
        if request.ttft is not None:
            self.ttft_samples.append(request.ttft)

    def record_finish(self, request):
        if request.finish_reason == FINISH_UNHEALTHY:
            # accounted under shed["unhealthy_slot"]: it must not also count
            # as finished (the shed/finished split partitions offered
            # requests) and its latency samples are poison — including the
            # TTFT recorded at first-token time, before the poisoning showed
            if request.ttft is not None:
                try:
                    self.ttft_samples.remove(request.ttft)
                except ValueError:
                    pass
            return
        self.finished += 1
        if request.tpot is not None:
            self.tpot_samples.append(request.tpot)

    def record_health_step(self, n_bad_slots):
        """Once per decode step (or poisoned prefill): how many ACTIVE
        computations produced non-finite logits (freed slots decode garbage
        by design and don't count)."""
        if n_bad_slots:
            self.nonfinite_logit_steps += 1

    def record_unhealthy(self):
        self.unhealthy_slots += 1

    def record_preempt(self):
        self.preempted += 1

    def observe_step(self, queue_depth, active_slots):
        """Once per scheduler step; periodically flushes monitor events."""
        self.steps += 1
        self._queue_depth = queue_depth
        self._active_slots = active_slots
        self.active_slots_peak = max(self.active_slots_peak, active_slots)
        if self.monitor is not None and getattr(self.monitor, "enabled", False) \
                and self.interval > 0 and self.steps % self.interval == 0:
            self.emit_events()

    # -- rollups ------------------------------------------------------------
    @property
    def elapsed(self):
        return max(self.clock.now() - self.start_time, 1e-9)

    @property
    def tokens_per_s(self):
        return self._window_tokens / self.elapsed

    @property
    def shed_total(self):
        return sum(self.shed.values())

    @property
    def shed_rate(self):
        # offered = admitted + admission-time sheds; unhealthy_slot sheds
        # were ALREADY admitted (counted in submitted), so they move a
        # request from finished to shed without growing the denominator
        total = self.submitted + self.shed_total - self.unhealthy_slots
        return self.shed_total / total if total else 0.0

    def snapshot(self):
        to_ms = lambda v: None if v is None else v * 1e3
        return {
            "submitted": self.submitted,
            "finished": self.finished,
            "shed": dict(self.shed),
            "shed_rate": round(self.shed_rate, 4),
            "total_tokens": self.total_tokens,
            "tokens_per_s": round(self.tokens_per_s, 2),
            "ttft_ms": {
                "p50": to_ms(percentile(self.ttft_samples, 50)),
                "p99": to_ms(percentile(self.ttft_samples, 99)),
            },
            "tpot_ms": {
                "p50": to_ms(percentile(self.tpot_samples, 50)),
                "p99": to_ms(percentile(self.tpot_samples, 99)),
            },
            "steps": self.steps,
            "queue_depth": self._queue_depth,
            "slot_occupancy": self._active_slots / max(self.n_slots, 1),
            "active_slots_peak": self.active_slots_peak,
            "preempted": self.preempted,
            "health": {
                "nonfinite_logit_steps": self.nonfinite_logit_steps,
                "unhealthy_slots": self.unhealthy_slots,
            },
            **({"kv_pool": self.kv_pool()} if self.kv_pool is not None
               else {}),
            **({"router": self.router()} if self.router is not None
               else {}),
        }

    def emit_events(self):
        """Write Serving/* scalars through the monitor fan-out (rank 0 only,
        same as Train/* and Comm/*)."""
        if self.monitor is None:
            return
        events = [
            ("Serving/queue_depth", float(self._queue_depth), self.steps),
            ("Serving/slot_occupancy",
             self._active_slots / max(self.n_slots, 1), self.steps),
            ("Serving/tokens_per_s", self.tokens_per_s, self.steps),
            ("Serving/shed_total", float(self.shed_total), self.steps),
            ("Serving/health_nonfinite_steps",
             float(self.nonfinite_logit_steps), self.steps),
            ("Serving/health_unhealthy_slots",
             float(self.unhealthy_slots), self.steps),
        ]
        if self.kv_pool is not None:
            kv = self.kv_pool()
            events += [
                ("Serving/kv_occupancy", float(kv["occupancy"]), self.steps),
                ("Serving/kv_fragmentation", float(kv["fragmentation"]),
                 self.steps),
                ("Serving/kv_capacity_tokens",
                 float(kv["capacity_tokens"]), self.steps),
                ("Serving/prefix_hit_rate", float(kv["prefix_hit_rate"]),
                 self.steps),
            ]
        p50 = percentile(self.ttft_samples, 50)
        if p50 is not None:
            events.append(("Serving/ttft_ms", p50 * 1e3, self.steps))
        p50t = percentile(self.tpot_samples, 50)
        if p50t is not None:
            events.append(("Serving/tpot_ms", p50t * 1e3, self.steps))
        self.monitor.write_events(events)
