"""Serving metrics: TTFT/TPOT histograms, throughput, occupancy, shed rate.

Mirrors the training engine's ``Comm/*_gb`` monitor pattern: the serving loop
records samples host-side and periodically writes ``Serving/*`` scalar events
through the existing ``monitor/`` fan-out (TensorBoard/W&B/CSV), gated on the
same monitor config sections. ``snapshot()`` is the machine-readable rollup
the load bench commits as its throughput–latency artifact.
"""

import collections

from ..telemetry.digest import LatencyDigest, evaluate_slo
from .request import FINISH_UNHEALTHY


def percentile(samples, q):
    """Nearest-rank percentile (q in [0, 100]); None on no samples."""
    if not samples:
        return None
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def slo_digest_events(digests, goodput_frac, slo, step, tracer=None,
                      counter=None):
    """Digest-derived P99 + goodput scalars and the slo_violation event —
    shared by the per-replica ServingMetrics cadence and the Router's
    fleet-merged cadence (same names, different monitor). The emitted P99
    IS the digest quantile: tier-1 pins it equal to the snapshot and to a
    digest rebuilt from the merged trace. ``counter``: object whose
    ``slo_violations`` tallies emit intervals with a violated target."""
    events = []
    for name, d in digests.items():
        p99 = d.quantile_ms(99)
        if p99 is not None:
            events.append((f"Serving/{name}_p99_ms", p99, step))
    events.append(("Serving/goodput_frac", float(goodput_frac), step))
    targets = slo.targets_ms() if slo is not None else {}
    grade = evaluate_slo(targets, digests)
    if grade["configured"]:
        burn = max(grade["burn_rate"].values(), default=0.0)
        events.append(("Serving/slo_burn_rate", burn, step))
        if not grade["pass"]:
            if counter is not None:
                counter.slo_violations += 1
            if tracer is not None:
                for metric, bad in grade["violated"].items():
                    if not bad:
                        continue
                    tracer.instant(
                        "slo/violation", cat="serving", metric=metric,
                        observed_p99_ms=grade["observed_p99_ms"][metric],
                        target_ms=grade["targets_ms"][metric],
                        burn_rate=grade["burn_rate"][metric])
        if counter is not None:
            events.append(("Serving/slo_violations",
                           float(counter.slo_violations), step))
    return events


class ServingMetrics:
    def __init__(self, n_slots, clock, monitor=None, interval=32,
                 kv_pool=None, slo=None, tracer=None):
        self.n_slots = n_slots
        self.clock = clock
        self.monitor = monitor
        self.interval = int(interval)
        # paged KV pool stats source (KVPoolManager.stats): block occupancy,
        # internal fragmentation, prefix hit rate — the memory-side truth
        # the slot-occupancy number no longer tells under paging
        self.kv_pool = kv_pool
        # router stats source (Router._router_stats, installed when this
        # replica registers with a Router): snapshot()["router"] then shows
        # the cross-replica view, coherent with the Serving/router_* events
        self.router = None
        self.start_time = clock.now()
        self._started = False       # start_time re-pins at first activity
        self._window_tokens = 0     # tokens since the last reset_window()
        self.total_tokens = 0
        self.submitted = 0
        self.finished = 0
        self.shed = collections.Counter()
        self.ttft_samples = []     # seconds (or virtual units)
        self.tpot_samples = []
        self.steps = 0
        self._queue_depth = 0
        self._active_slots = 0
        self.active_slots_peak = 0   # paged pool's ">= 2x effective slots" pin
        # numerics health (fed by the decode program's in-graph
        # nonfinite-logit count; see serving/engine.py _decode_once)
        self.nonfinite_logit_steps = 0  # decode steps with >=1 bad active slot
        self.unhealthy_slots = 0        # requests shed via unhealthy_slot
        # on-demand growth: requests preempted back to the queue on pool
        # exhaustion (they resume; NOT part of the shed/finished partition)
        self.preempted = 0
        # streaming SLO percentiles: mergeable fixed-bucket digests next to
        # the exact sample lists (the lists stay the PR 4 trace==metrics
        # currency; the digests are what rolls up across replicas and what
        # the Serving/*_p99_ms events and serving.slo grading read)
        self.ttft_digest = LatencyDigest()
        self.tpot_digest = LatencyDigest()
        self.queue_wait_digest = LatencyDigest()
        # serving.slo block (None/unarmed = no objectives) + the tracer the
        # structured slo/violation events ride (set by the engine after its
        # tracer exists)
        self.slo = slo
        self.tracer = tracer
        self.slo_violations = 0   # emit intervals with >=1 violated target
        self.window_resets = 0    # reset_window() calls (warmup exclusion)
        # multi-tenant QoS: per-tenant counters + latency digests, keyed by
        # tenant_id (populated lazily — a single-tenant engine pays one
        # "default" entry). Counters are CUMULATIVE (survive reset_window,
        # like submitted/finished/shed); the digests reset with the window
        # under the same epoch guard the global digests use, so a warmup
        # cannot pollute a tenant's SLO grade. tenants_cfg (set by the
        # engine when serving.tenants is configured) supplies per-class
        # ttft_p99_ms overrides for the per-tenant grade.
        self.tenants = {}
        self.tenants_cfg = None
        # degraded-mode hook (set by the engine when serving.degraded is
        # armed): a callable returning the current ladder level, mirrored
        # as the Serving/degraded_level scalar on the emit cadence
        self.degraded = None
        # full-ladder state for snapshot()["degraded"] (level, rung,
        # residency, transitions) — set alongside ``degraded``
        self.degraded_snapshot = None
        # priority preemptions: evictions of a batch-class stream by an
        # interactive arrival (a subset of ``preempted``)
        self.priority_evictions = 0
        # goodput accounting, in DEVICE TOKENS of work (the virtual cost
        # model's currency: one prefill dispatch costs its padded length,
        # one decode step yields one token per active slot). useful = fresh
        # prefill positions + decode tokens; wasted = preemption replay +
        # bucket padding; prefix-cache savings are work NEVER dispatched
        # (reported, not part of the frac).
        self.prefill_device_tokens = 0
        self.replay_tokens = 0
        self.padding_tokens = 0
        self.prefix_saved_tokens = 0
        self.decode_tokens = 0
        # speculative decoding (serving/speculative.py): candidate tokens
        # drafted, accepted by the one-forward verify, and rolled back,
        # plus the dispatch counter accepted_tokens_per_step is measured
        # against (decode + verify program dispatches — the denominator of
        # the ">1 effective decode tokens per step" claim). Armed by the
        # engine when serving.speculative is enabled (gates the
        # Serving/spec_* monitor events).
        self.speculative_armed = False
        self.drafted_tokens = 0
        self.accepted_tokens = 0
        self.rolled_back_tokens = 0
        self.verify_steps = 0
        self.decode_dispatches = 0
        # live KV migration (serving/migration.py): snapshots captured on
        # this replica, requests migrated out (drain) / spliced in, and the
        # positions a splice restored WITHOUT recompute (work avoided, like
        # prefix_saved_tokens — reported, not part of the goodput frac)
        self.kv_snapshots = 0
        self.migrations_out = 0
        self.migrations_in = 0
        self.migrated_saved_tokens = 0

    # -- recording ----------------------------------------------------------
    def _mark_started(self):
        # the throughput window opens at the FIRST request, not at engine
        # construction — a server idle for an hour must not dilute tokens/s
        if not self._started:
            self.start_time = self.clock.now()
            self._started = True

    def reset_window(self):
        """Re-open the measured window (e.g. after a warmup run): tokens/s
        reflects tokens since this call, and the streaming latency digests
        + goodput counters restart — a warmup's compile-time TTFTs would
        otherwise sit in the SLO grade forever (digests cannot age samples
        out). Cumulative counters (submitted/finished/shed/samples) keep
        the engine's lifetime story."""
        self.start_time = self.clock.now()
        self._started = True
        self._window_tokens = 0
        self.ttft_digest = LatencyDigest()
        self.tpot_digest = LatencyDigest()
        self.queue_wait_digest = LatencyDigest()
        self.prefill_device_tokens = 0
        self.replay_tokens = 0
        self.padding_tokens = 0
        self.prefix_saved_tokens = 0
        self.decode_tokens = 0
        # the speculative window restarts with the goodput window: the
        # accepted-tokens-per-step ratio must cover the same steps as its
        # decode_tokens numerator
        self.drafted_tokens = 0
        self.accepted_tokens = 0
        self.rolled_back_tokens = 0
        self.verify_steps = 0
        self.decode_dispatches = 0
        # per-tenant digests restart with the window too (same epoch), but
        # the per-tenant COUNTERS survive — a warmup reset must not erase
        # who submitted/was shed, only the latency samples it polluted
        for t in self.tenants.values():
            t["ttft_digest"] = LatencyDigest()
            t["tpot_digest"] = LatencyDigest()
        # recorded so trace readers know the live digests no longer cover
        # the whole trace (fleet_report downgrades its digest-coherence
        # gate to informational when a reset happened mid-run)
        self.window_resets += 1

    def _tenant(self, request):
        t = self.tenants.get(request.tenant_id)
        if t is None:
            t = self.tenants[request.tenant_id] = {
                "class": request.tenant_class,
                "submitted": 0, "finished": 0, "tokens": 0,
                "shed": collections.Counter(),
                "ttft_digest": LatencyDigest(),
                "tpot_digest": LatencyDigest(),
            }
        return t

    def record_submit(self, request=None):
        self._mark_started()
        self.submitted += 1
        if request is not None:
            self._tenant(request)["submitted"] += 1

    def record_shed(self, reason, request=None):
        self._mark_started()
        self.shed[reason] += 1
        if request is not None:
            self._tenant(request)["shed"][reason] += 1

    def record_tokens(self, n, request=None):
        self.total_tokens += int(n)
        self._window_tokens += int(n)
        if request is not None:
            self._tenant(request)["tokens"] += int(n)

    def record_first_token(self, request):
        if request.ttft is not None:
            self.ttft_samples.append(request.ttft)
            self.ttft_digest.add(request.ttft)
            self._tenant(request)["ttft_digest"].add(request.ttft)
            request.ttft_epoch = self.window_resets

    def record_finish(self, request):
        if request.finish_reason == FINISH_UNHEALTHY:
            # accounted under shed["unhealthy_slot"]: it must not also count
            # as finished (the shed/finished split partitions offered
            # requests) and its latency samples are poison — including the
            # TTFT recorded at first-token time, before the poisoning showed
            # the wide-event partition excludes unhealthy requests from
            # EVERY latency field — the live digests must match or the
            # trace==digest coherence gate false-alarms. Epoch guards: a
            # sample recorded BEFORE a reset_window() lives in a discarded
            # digest; retracting it from the fresh one would decrement a
            # different (healthy) request's same-bucket sample instead.
            if request.ttft is not None:
                try:
                    self.ttft_samples.remove(request.ttft)
                except ValueError:
                    pass
                if request.ttft_epoch == self.window_resets:
                    self.ttft_digest.remove(request.ttft)
                    self._tenant(request)["ttft_digest"].remove(request.ttft)
            if request.queue_wait is not None \
                    and request.queue_wait_epoch == self.window_resets:
                self.queue_wait_digest.remove(request.queue_wait)
            return
        self.finished += 1
        self._tenant(request)["finished"] += 1
        if request.tpot is not None:
            self.tpot_samples.append(request.tpot)
            self.tpot_digest.add(request.tpot)
            self._tenant(request)["tpot_digest"].add(request.tpot)

    def record_queue_wait(self, request):
        """Arrival -> first prefill dispatch (recorded once per request, at
        its FIRST start; preemption resumes don't reopen the window)."""
        if request.queue_wait is not None:
            self.queue_wait_digest.add(request.queue_wait)
            request.queue_wait_epoch = self.window_resets

    def record_prefill_work(self, padded_len, true_len, replay=0):
        """One prefill dispatch: ``padded_len`` device tokens paid, of which
        ``true_len`` were real positions (``replay`` of those re-computing
        work a preemption threw away) and the rest bucket padding.
        (``prefix_saved_tokens`` is bumped at the hit site — it is work
        never dispatched, so it has no padded/true split.)"""
        self.prefill_device_tokens += int(padded_len)
        self.padding_tokens += int(padded_len) - int(true_len)
        self.replay_tokens += int(replay)

    def record_decode_tokens(self, n):
        self.decode_tokens += int(n)

    def record_decode_dispatch(self):
        """One decode-program dispatch (plain decode OR speculative
        verify): the denominator of ``accepted_tokens_per_step``."""
        self.decode_dispatches += 1

    def record_draft(self, n):
        self.drafted_tokens += int(n)

    def record_accept(self, accepted, rejected):
        self.accepted_tokens += int(accepted)
        self.rolled_back_tokens += int(rejected)

    def record_verify_step(self):
        self.verify_steps += 1

    @property
    def accept_rate(self):
        """Accepted / drafted candidate tokens (0.0 before any draft)."""
        return self.accepted_tokens / self.drafted_tokens \
            if self.drafted_tokens else 0.0

    @property
    def accepted_tokens_per_step(self):
        """Decode tokens emitted per decode-program dispatch (verify steps
        included) — strictly > 1 exactly when acceptance is doing work:
        the speculative multiplier on effective decode throughput."""
        return self.decode_tokens / self.decode_dispatches \
            if self.decode_dispatches else 0.0

    def speculative_snapshot(self):
        return {
            "drafted_tokens": self.drafted_tokens,
            "accepted_tokens": self.accepted_tokens,
            "rolled_back_tokens": self.rolled_back_tokens,
            "verify_steps": self.verify_steps,
            "decode_dispatches": self.decode_dispatches,
            "accept_rate": round(self.accept_rate, 4),
            "accepted_tokens_per_step": round(
                self.accepted_tokens_per_step, 4),
        }

    def record_snapshot(self):
        self.kv_snapshots += 1

    def record_migration_out(self):
        self.migrations_out += 1

    def record_migration_in(self, saved_tokens=0):
        self.migrations_in += 1
        self.migrated_saved_tokens += int(saved_tokens)

    def migration_snapshot(self):
        return {
            "kv_snapshots": self.kv_snapshots,
            "migrations_out": self.migrations_out,
            "migrations_in": self.migrations_in,
            "migrated_saved_tokens": self.migrated_saved_tokens,
        }

    def record_health_step(self, n_bad_slots):
        """Once per decode step (or poisoned prefill): how many ACTIVE
        computations produced non-finite logits (freed slots decode garbage
        by design and don't count)."""
        if n_bad_slots:
            self.nonfinite_logit_steps += 1

    def record_unhealthy(self):
        self.unhealthy_slots += 1

    def record_preempt(self, priority=False):
        self.preempted += 1
        if priority:
            self.priority_evictions += 1

    def observe_step(self, queue_depth, active_slots):
        """Once per scheduler step; periodically flushes monitor events."""
        self.steps += 1
        self._queue_depth = queue_depth
        self._active_slots = active_slots
        self.active_slots_peak = max(self.active_slots_peak, active_slots)
        if self.monitor is not None and getattr(self.monitor, "enabled", False) \
                and self.interval > 0 and self.steps % self.interval == 0:
            self.emit_events()

    # -- rollups ------------------------------------------------------------
    @property
    def elapsed(self):
        return max(self.clock.now() - self.start_time, 1e-9)

    @property
    def tokens_per_s(self):
        return self._window_tokens / self.elapsed

    @property
    def shed_total(self):
        return sum(self.shed.values())

    @property
    def goodput_frac(self):
        """Useful device tokens / total device tokens. Useful = fresh
        prefill positions + decode tokens; wasted = preemption replay +
        prefill bucket padding. 1.0 before any work."""
        total = self.prefill_device_tokens + self.decode_tokens
        if total == 0:
            return 1.0
        useful = total - self.replay_tokens - self.padding_tokens
        return useful / total

    def goodput_snapshot(self):
        return {
            "prefill_device_tokens": self.prefill_device_tokens,
            "decode_tokens": self.decode_tokens,
            "replay_tokens": self.replay_tokens,
            "padding_tokens": self.padding_tokens,
            "prefix_saved_tokens": self.prefix_saved_tokens,
            "wasted_tokens": self.replay_tokens + self.padding_tokens,
            "goodput_frac": round(self.goodput_frac, 4),
        }

    def latency_digests(self):
        """The metric->digest map evaluate_slo and the fleet rollup read."""
        return {"ttft": self.ttft_digest, "tpot": self.tpot_digest,
                "queue_wait": self.queue_wait_digest}

    def tenant_slo_targets(self, tenant_class):
        """SLO targets for a tenant's grade: the serving.slo targets, with
        the class's ``ttft_p99_ms`` override (serving.tenants.<class>)
        taking precedence when configured."""
        targets = dict(self.slo.targets_ms()) if self.slo is not None else {}
        if self.tenants_cfg is not None:
            cc = self.tenants_cfg.class_config(tenant_class)
            if cc is not None and cc.ttft_p99_ms > 0:
                targets["ttft_p99_ms"] = cc.ttft_p99_ms
        return targets

    def tenancy_snapshot(self):
        """Per-tenant rollup: counters, per-tenant P99s off the tenant
        digests, and an SLO grade against the class's targets — the
        ``tenancy`` block in snapshot()/fleet.json/bench artifacts."""
        out = {}
        for tid in sorted(self.tenants):
            t = self.tenants[tid]
            digests = {"ttft": t["ttft_digest"], "tpot": t["tpot_digest"]}
            out[tid] = {
                "class": t["class"],
                "submitted": t["submitted"],
                "finished": t["finished"],
                "shed": dict(t["shed"]),
                "tokens": t["tokens"],
                "ttft_p99_ms": t["ttft_digest"].quantile_ms(99),
                "tpot_p99_ms": t["tpot_digest"].quantile_ms(99),
                "slo": evaluate_slo(
                    self.tenant_slo_targets(t["class"]), digests),
            }
        return out

    def slo_eval(self):
        """Grade the digests against serving.slo (configured: False block
        when no slo config / no targets)."""
        targets = self.slo.targets_ms() if self.slo is not None else {}
        return evaluate_slo(targets, self.latency_digests())

    @property
    def shed_rate(self):
        # offered = admitted + admission-time sheds; unhealthy_slot sheds
        # were ALREADY admitted (counted in submitted), so they move a
        # request from finished to shed without growing the denominator
        total = self.submitted + self.shed_total - self.unhealthy_slots
        return self.shed_total / total if total else 0.0

    def snapshot(self):
        to_ms = lambda v: None if v is None else v * 1e3
        return {
            "submitted": self.submitted,
            "finished": self.finished,
            "shed": dict(self.shed),
            "shed_rate": round(self.shed_rate, 4),
            "total_tokens": self.total_tokens,
            "tokens_per_s": round(self.tokens_per_s, 2),
            "ttft_ms": {
                "p50": to_ms(percentile(self.ttft_samples, 50)),
                "p99": to_ms(percentile(self.ttft_samples, 99)),
            },
            "tpot_ms": {
                "p50": to_ms(percentile(self.tpot_samples, 50)),
                "p99": to_ms(percentile(self.tpot_samples, 99)),
            },
            # streaming-digest percentiles (mergeable across replicas; the
            # SAME numbers the Serving/*_p99_ms events and slo grade carry)
            "percentiles": {
                name + "_ms": d.percentiles_ms()
                for name, d in self.latency_digests().items()},
            "goodput": self.goodput_snapshot(),
            "speculative": self.speculative_snapshot(),
            "migration": self.migration_snapshot(),
            "slo": self.slo_eval(),
            "tenancy": self.tenancy_snapshot(),
            "steps": self.steps,
            "queue_depth": self._queue_depth,
            "priority_evictions": self.priority_evictions,
            "slot_occupancy": self._active_slots / max(self.n_slots, 1),
            "active_slots_peak": self.active_slots_peak,
            "preempted": self.preempted,
            "health": {
                "nonfinite_logit_steps": self.nonfinite_logit_steps,
                "unhealthy_slots": self.unhealthy_slots,
            },
            **({"degraded": self.degraded_snapshot()}
               if self.degraded_snapshot is not None else {}),
            **({"kv_pool": self.kv_pool()} if self.kv_pool is not None
               else {}),
            **({"router": self.router()} if self.router is not None
               else {}),
        }

    def emit_events(self):
        """Write Serving/* scalars through the monitor fan-out (rank 0 only,
        same as Train/* and Comm/*)."""
        if self.monitor is None:
            return
        events = [
            ("Serving/queue_depth", float(self._queue_depth), self.steps),
            ("Serving/slot_occupancy",
             self._active_slots / max(self.n_slots, 1), self.steps),
            ("Serving/tokens_per_s", self.tokens_per_s, self.steps),
            ("Serving/shed_total", float(self.shed_total), self.steps),
            ("Serving/health_nonfinite_steps",
             float(self.nonfinite_logit_steps), self.steps),
            ("Serving/health_unhealthy_slots",
             float(self.unhealthy_slots), self.steps),
        ]
        if self.kv_pool is not None:
            kv = self.kv_pool()
            events += [
                ("Serving/kv_occupancy", float(kv["occupancy"]), self.steps),
                ("Serving/kv_fragmentation", float(kv["fragmentation"]),
                 self.steps),
                ("Serving/kv_capacity_tokens",
                 float(kv["capacity_tokens"]), self.steps),
                ("Serving/prefix_hit_rate", float(kv["prefix_hit_rate"]),
                 self.steps),
                # which decode-attention path produced these numbers
                # (1 = the fused paged kernel, 0 = the gather path) —
                # coherent with snapshot()["kv_pool"]["attention_backend"]
                ("Serving/kv_attention_fused",
                 1.0 if kv.get("attention_backend") == "fused" else 0.0,
                 self.steps),
            ]
        if self.speculative_armed:
            # coherent with snapshot()["speculative"] by construction (the
            # PR 4 trace==metrics discipline, asserted tier-1)
            events.append(("Serving/spec_accept_rate",
                           float(self.accept_rate), self.steps))
            events.append(("Serving/spec_accepted_tokens_per_step",
                           float(self.accepted_tokens_per_step), self.steps))
        if self.degraded is not None:
            events.append(("Serving/degraded_level",
                           float(self.degraded()), self.steps))
        p50 = percentile(self.ttft_samples, 50)
        if p50 is not None:
            events.append(("Serving/ttft_ms", p50 * 1e3, self.steps))
        p50t = percentile(self.tpot_samples, 50)
        if p50t is not None:
            events.append(("Serving/tpot_ms", p50t * 1e3, self.steps))
        events.extend(slo_digest_events(
            self.latency_digests(), self.goodput_frac, self.slo, self.steps,
            tracer=self.tracer, counter=self))
        self.monitor.write_events(events)
