"""Request & event types for the continuous-batching serving layer.

A ``Request`` is the unit the scheduler moves through QUEUED -> RUNNING ->
FINISHED (or straight to REJECTED at admission); ``TokenEvent`` is the unit
the streaming API yields — one per generated token per request, tagged with
``done`` + ``finish_reason`` on the last one.
"""

import dataclasses
import enum
import typing

import numpy as np


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    REJECTED = "rejected"


# admission-control shed reasons (reject-with-reason instead of OOM)
REJECT_QUEUE_FULL = "queue_full"
REJECT_PROMPT_TOO_LONG = "prompt_too_long"
REJECT_BAD_REQUEST = "bad_request"
# paged KV pool: the request's block footprint exceeds the pool's capacity
REJECT_NO_FREE_BLOCKS = "no_free_blocks"
# router tier: every replica is draining or at queue capacity — the
# cross-replica generalization of queue_full
REJECT_ALL_REPLICAS_SATURATED = "all_replicas_saturated"
# router tier, terminal failover fallback: the request's replica died (or
# kept failing) and the bounded retry budget (serving.retry_limit) is spent
# — or no surviving replica could take it
REJECT_REPLICA_FAILED = "replica_failed"
# degraded-mode ladder (serving.degraded): the engine is shedding this
# request's CLASS under SLO burn — batch from rung 1, interactive only at
# the last rung (per-tenant shed counters pin the ordering)
REJECT_DEGRADED = "degraded"

# tenant/priority classes (serving.tenants)
CLASS_INTERACTIVE = "interactive"
CLASS_BATCH = "batch"

FINISH_EOS = "eos"
FINISH_LENGTH = "length"
FINISH_STOP = "stop"
# health watchdog shed: the slot's logits went non-finite mid-decode
FINISH_UNHEALTHY = "unhealthy_slot"


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling knobs, threaded through ``sample_token`` as traced
    per-slot arrays — co-batched requests never share an rng stream or a
    temperature. ``temperature <= 0`` means greedy."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: typing.Optional[int] = None


@dataclasses.dataclass
class Request:
    prompt: np.ndarray                      # [prompt_len] int32
    max_new_tokens: int = 32
    sampling: SamplingParams = None
    eos_token_id: typing.Optional[int] = None
    stop_token_ids: typing.Tuple[int, ...] = ()
    request_id: typing.Optional[int] = None  # assigned at submit if None
    # open-loop offered-load arrival, as an OFFSET from serve()/submit() time
    # (resolved against the clock at intake); None = already arrived
    arrival_time: typing.Optional[float] = None
    # set once arrival_time has been converted to an absolute clock value —
    # submit() must not re-shift a request serve() already resolved
    arrival_resolved: bool = False
    # router session affinity: requests sharing a session_id stick to one
    # replica (None = stateless, routed purely on load/prefix affinity)
    session_id: typing.Optional[str] = None
    # cross-replica trace id: every span/instant this request produces on
    # any replica carries it, so the fleet merger can stitch one lifecycle
    # from N per-replica streams (assigned at router/engine submit if None)
    trace_id: typing.Optional[str] = None
    # multi-tenant QoS (serving.tenants): the paying tenant and its
    # priority class. "interactive" rides the latency SLO (and may evict a
    # batch stream under priority preemption); "batch" is throughput
    # traffic — first shed under the degraded ladder, first evicted under
    # slot pressure. Per-tenant digests/budgets/sheds key on tenant_id.
    tenant_id: str = "default"
    tenant_class: str = CLASS_INTERACTIVE

    # -- scheduler-owned runtime fields -------------------------------------
    state: RequestState = RequestState.QUEUED
    reject_reason: typing.Optional[str] = None
    finish_reason: typing.Optional[str] = None
    tokens: list = dataclasses.field(default_factory=list)
    slot: typing.Optional[int] = None
    submit_time: typing.Optional[float] = None
    first_token_time: typing.Optional[float] = None
    finish_time: typing.Optional[float] = None
    # on-demand growth preemption: times this request was preempted back to
    # the queue, and the per-slot rng key captured at preemption so the
    # resumed stream continues bitwise-identically (greedy AND sampled)
    preemptions: int = 0
    # of those, evictions by a higher-priority (interactive) arrival under
    # serving.tenants.preempt — a subset of ``preemptions``
    priority_evictions: int = 0
    resume_rng: typing.Optional[np.ndarray] = None
    # admission-time KV block reservation held in KVPoolManager._pending
    # until the slot insert consumes it (or an early finish cancels it)
    reserved_blocks: int = 0
    # first slot-bind order (preemption victim = newest; a resumed request
    # keeps its original seniority)
    admit_seq: int = -1
    # scheduler admission time (next_admissions stamp) and first prefill
    # dispatch time — queue_wait's endpoint; survives preemption (a resume
    # replay does not reopen the queue-wait window)
    admit_time: typing.Optional[float] = None
    prefill_start_time: typing.Optional[float] = None
    # digest window epochs: ServingMetrics.window_resets at the moment each
    # latency sample was recorded, so an unhealthy-shed retraction after a
    # reset_window() cannot decrement a fresh digest's (different) sample
    ttft_epoch: int = -1
    queue_wait_epoch: int = -1
    # goodput accounting (summed into ServingMetrics.goodput, emitted in
    # the request/finish instant so the wide event carries them verbatim):
    # positions re-prefilled after a preemption, prefill bucket padding
    # beyond the true token count, positions skipped via prefix-cache hits,
    # prefill chunk dispatches, and the KV-block high-water mark
    replay_tokens: int = 0
    padding_tokens: int = 0
    prefix_saved_tokens: int = 0
    chunks: int = 0
    kv_blocks_peak: int = 0
    # speculative decoding (serving/speculative.py): candidate tokens the
    # drafter proposed for this request, how many were accepted by the
    # one-forward verify, and how many rolled back — emitted verbatim in
    # the request/finish instant so the fleet wide event reconciles with
    # the Serving/spec_* counters
    drafted_tokens: int = 0
    accepted_tokens: int = 0
    rolled_back_tokens: int = 0
    # live KV migration (serving/migration.py): the latest portable
    # RequestSnapshot of this request's device state — captured on the
    # periodic cadence (serving.migration.snapshot_interval_tokens) or at
    # drain-by-migration; a target replica splices it instead of replaying
    migration: typing.Optional[object] = None
    # fleet recovery accounting, all counted distinctly in RouterMetrics:
    # cross-replica re-dispatches after a replica failure (bounded by
    # serving.retry_limit), cross-replica retries after an unhealthy_slot
    # shed (same budget, separate counter), and completed replica moves
    # (drain-by-migration + failover splices/replays)
    failovers: int = 0
    retries: int = 0
    migrations: int = 0
    # disaggregated fleet (serving.pools): completed first-token
    # prefill->decode handoffs, and the in-flight marker the router sets so
    # the decode-side splice emits the handoff_in instant (cleared there);
    # rebalances counts voluntary mid-flight moves off hot replicas
    handoffs: int = 0
    handoff_pending: bool = False
    rebalances: int = 0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.sampling is None:
            self.sampling = SamplingParams()
        elif isinstance(self.sampling, dict):
            self.sampling = SamplingParams(**self.sampling)

    @property
    def prompt_len(self):
        return int(self.prompt.shape[0])

    def reset_for_retry(self):
        """Clear the terminal state an ``unhealthy_slot`` shed left so the
        router can re-dispatch this request to a DIFFERENT replica. Safe by
        construction: the unhealthy shed fires BEFORE the first token
        streams, so nothing user-visible rewinds."""
        self.state = RequestState.QUEUED
        self.reject_reason = None
        self.finish_reason = None
        self.finish_time = None
        self.slot = None

    @property
    def start_time(self):
        """The latency zero point every per-request metric shares: resolved
        arrival if the request carried one, else submit time."""
        return self.arrival_time if self.arrival_time is not None \
            else self.submit_time

    @property
    def ttft(self):
        """Time from arrival (resolved by serve()) or submit to first token —
        queueing delay counts, as a serving frontend's user would see it."""
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.start_time

    @property
    def queue_wait(self):
        """Arrival (or submit) to the first prefill dispatch — the pure
        queueing component of TTFT (TTFT = queue_wait + prefill +
        first-token sample, all on the scheduler clock)."""
        if self.prefill_start_time is None:
            return None
        return self.prefill_start_time - self.start_time

    @property
    def tpot(self):
        """Mean time per output token after the first."""
        if self.finish_time is None or self.first_token_time is None \
                or len(self.tokens) < 2:
            return None
        return (self.finish_time - self.first_token_time) / (len(self.tokens) - 1)


@dataclasses.dataclass
class TokenEvent:
    """One streamed token: ``index`` is the 0-based position in the request's
    generated stream; the final event carries ``done=True`` + a reason."""

    request_id: int
    token: int
    index: int
    done: bool = False
    finish_reason: typing.Optional[str] = None
    time: float = 0.0


def as_request(obj, default_max_new_tokens=32):
    """Coerce a user-supplied request (Request | dict | array prompt)."""
    if isinstance(obj, Request):
        return obj
    if isinstance(obj, dict):
        d = dict(obj)
        d.setdefault("max_new_tokens", default_max_new_tokens)
        return Request(**d)
    return Request(prompt=np.asarray(obj),
                   max_new_tokens=default_max_new_tokens)
