"""Live KV migration: portable request snapshots for the serving fleet.

A :class:`RequestSnapshot` is everything a running request needs to continue
on a DIFFERENT replica, captured between scheduler steps:

- the physical pool blocks holding positions ``[0, pos)`` as RAW pool-dtype
  bytes — int8 payloads move with their f32 scales instead of being
  dequantized, because a dequantize -> requantize round trip reproduces the
  payload but can perturb the recomputed scale in its last ulp, which would
  break the migrated-stream-is-bitwise contract;
- the block-table row order (implicit: blocks are stacked in row order);
- the cursor, the per-slot rng chain key, the committed tokens, and the
  sampling knobs (the same state tuple PR 12's preempt/resume moves through
  the queue, plus the device bytes so nothing is recomputed);
- the prompt's SHA-256 prefix chain keys, so the target replica can dedupe
  the spliced blocks against its own prefix cache (shared blocks are taken
  by reference, only the private suffix is copied).

The engine side (``ServingEngine.capture_snapshot`` / the splice branch in
``_start_request``) owns the device programs; this module owns the portable
container and the host-side rng re-derivation used when a snapshot is STALE
(periodic-cadence snapshots under replica-kill recovery) or absent.
"""

import numpy as np

__all__ = ["RequestSnapshot", "advance_rng"]


class RequestSnapshot:
    """Portable mid-stream state of one serving request.

    ``blocks`` maps every paged-pool leaf name (``k``, ``v`` and, for int8
    pools, ``k_scale``/``v_scale``) to a host array ``[L, NB, bs, kvh, *]``
    in block-table-row order: source block ``j`` covers positions
    ``[j*bs, (j+1)*bs)``. Only the first :attr:`full_blocks` source blocks
    are ever injected — the capture cursor may sit mid-block, and a partial
    block is cheaper to replay (<= ``block_size`` tokens) than to splice
    with a positional fix-up program.
    """

    __slots__ = ("request_id", "prompt", "tokens", "pos", "rng", "blocks",
                 "block_size", "chain_keys", "temperature", "top_k", "top_p",
                 "seed", "max_new_tokens", "eos_token_id", "geometry")

    def __init__(self, *, request_id, prompt, tokens, pos, rng, blocks,
                 block_size, chain_keys, temperature, top_k, top_p, seed,
                 max_new_tokens, eos_token_id, geometry):
        self.request_id = request_id
        self.prompt = np.asarray(prompt, np.int32)
        self.tokens = tuple(int(t) for t in tokens)
        self.pos = int(pos)
        self.rng = np.asarray(rng, np.uint32).copy()
        self.blocks = blocks
        self.block_size = int(block_size)
        self.chain_keys = tuple(chain_keys)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = seed
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        # (n_layers, block_size, kv_heads, head_dim, leaf-dtype fingerprint):
        # a snapshot only splices into a pool with the SAME geometry —
        # anything else falls back to replay-resume
        self.geometry = tuple(geometry)

    @property
    def full_blocks(self):
        """Source blocks that are completely filled at the capture cursor
        (positions [0, full_blocks * block_size) are splice-able verbatim;
        the tail past that replays as a suffix prefill)."""
        return self.pos // self.block_size

    @property
    def nbytes(self):
        return sum(a.nbytes for a in self.blocks.values())

    def compatible_with(self, geometry):
        """Splice precondition: identical pool geometry AND at least one
        full source block (otherwise replay is strictly simpler)."""
        return tuple(geometry) == self.geometry and self.full_blocks > 0

    def __repr__(self):
        return (f"RequestSnapshot(request_id={self.request_id}, "
                f"pos={self.pos}, tokens={len(self.tokens)}, "
                f"full_blocks={self.full_blocks}, nbytes={self.nbytes})")


def advance_rng(rng, n_steps):
    """Advance a per-slot rng chain key by ``n_steps`` decode steps on the
    host — exactly what the compiled decode program does on device
    (``split(key)[1]`` once per dispatched step, one committed token per
    active step), so a SEEDED sampled stream resumed from a stale snapshot
    re-joins its original rng stream bitwise: the tokens committed after the
    capture are teacher-forced by the replay prefill, and the first fresh
    sample draws from the key the uninterrupted stream would have held.
    Greedy rows never consult the key, so over-advancing is harmless there.
    """
    if n_steps <= 0:
        return np.asarray(rng, np.uint32)
    import jax

    key = np.asarray(rng, np.uint32)
    for _ in range(int(n_steps)):
        key = np.asarray(jax.random.split(key)[1], np.uint32)
    return key
