"""Continuous-batching serving engine (the tentpole of the serving layer).

Orca-style iteration-level scheduling, TPU-native by construction: ONE jitted
decode program runs over a **fixed pool of batch slots** (static shapes,
compiled exactly once per (model, slot-pool) configuration). Each slot holds
one request's KV rows, cursor, last token, rng key and sampling knobs — all
as per-slot device arrays, so a finished request frees its slot mid-flight
and a queued one is prefilled (the existing bucketed ``prefill_flash`` path)
and spliced into the RUNNING decode batch with ``dynamic_update_slice``
(``models/decoding.py:insert_slot_kv``). No recompilation, no waiting for the
whole batch to drain — the serving-side half of DeepSpeed-Inference's
latency/throughput story (arXiv:2207.00032) on top of the kernel path.

Greedy streams are bitwise-identical to sequential ``generate()`` calls: the
per-slot decode runs the same ``forward_with_cache`` math at the same
positions over the same KV window (pinned in tier-1
``tests/unit/test_serving.py``).
"""

import collections
import dataclasses
from collections import OrderedDict

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config.base import ConfigError
from ..inference.engine import lru_compiled
from ..models.decoding import (extract_slot_blocks, forward_with_cache,
                               forward_with_paged_cache, gather_slot_cache,
                               init_cache, init_paged_cache, inject_block_kv,
                               insert_block_kv, insert_slot_kv,
                               reset_block_kv, reset_slot_kv, sample_token,
                               verify_with_paged_cache)
from ..utils.logging import log_dist
from .clock import VirtualClock, WallClock
from .kv_pool import GARBAGE_BLOCK, KVPoolManager, prefix_chain_keys
from .migration import RequestSnapshot, advance_rng
from .metrics import ServingMetrics
from .queue import RequestQueue
from .request import (CLASS_BATCH, CLASS_INTERACTIVE, FINISH_EOS,
                      FINISH_LENGTH, FINISH_STOP, FINISH_UNHEALTHY,
                      REJECT_DEGRADED, Request, RequestState, TokenEvent,
                      as_request)
from .scheduler import ServingScheduler


@dataclasses.dataclass
class _PrefillJob:
    """A prompt prefill in flight across scheduler steps (chunked prefill
    and/or preemption resume). The job owns its reserved slot and the
    partially-filled dense b=1 cache between chunks; ``pos`` is the next
    prompt position to prefill (``ids`` = prompt, or prompt + already-
    generated tokens on a resume replay)."""

    req: object
    slot: int
    cache: dict
    ids: np.ndarray          # full token sequence to prefill
    pos: int                 # next position to write (starts at shared_len)
    shared_len: int
    shared_blocks: list
    resume: bool             # replaying a preempted request: no first-token
    #                          sampling, stream/metrics continue where left

    @property
    def done(self):
        return self.pos >= len(self.ids)


class ServingEngine:
    """Slot-pool continuous batching over an ``InferenceEngine``'s weights."""

    def __init__(self, engine, serving_config=None, clock=None, monitor=None,
                 tracer=None):
        if not hasattr(engine.module, "config"):
            raise ConfigError(
                "serving needs a zoo-style model (config with kv cache "
                "geometry); an injection-policy-served unknown model "
                "supports forward() scoring only")
        self.engine = engine
        self.cfg = serving_config if serving_config is not None \
            else engine.config.serving
        self.n_slots = int(self.cfg.n_slots)
        self.max_len = int(self.cfg.max_len) or int(engine.config.max_tokens)
        if self.max_len > engine.config.max_tokens:
            raise ConfigError(
                f"serving.max_len {self.max_len} exceeds inference "
                f"max_tokens {engine.config.max_tokens}")
        self.clock = clock if clock is not None else (
            VirtualClock() if self.cfg.virtual_clock else WallClock())
        # paged KV pool (kv_pool.enabled): block allocator + prefix cache on
        # the host, block-table gathers on the device (serving/kv_pool.py)
        self.paged = bool(self.cfg.kv_pool.enabled)
        self.pool_mgr = KVPoolManager(self.cfg.kv_pool, self.n_slots,
                                      self.max_len) if self.paged else None
        # decode-attention backend: "dense" (no paging), "gather" (dense
        # per-slot view through the block table), or "fused" (the split-KV
        # flash-decode kernel walks the table in-kernel). A requested
        # "fused" is shape-probed ONCE here; unsupported shapes warn and
        # fall back to the gather path — serving never hard-fails on a
        # kernel constraint.
        self.attn_backend = "dense"
        if self.paged:
            self.attn_backend = self.cfg.kv_pool.attention_backend
            if self.attn_backend == "fused":
                from ..ops.pallas.paged_attention import \
                    fused_decode_supported

                ok, reason = fused_decode_supported(
                    engine.module.config, self.pool_mgr.block_size,
                    mp_world_size=max(engine.mp_world_size, 1),
                    kv_dtype=self.cfg.kv_pool.kv_dtype)
                if not ok:
                    log_dist(
                        "ServingEngine: kv_pool.attention_backend='fused' "
                        f"unsupported for this shape ({reason}); falling "
                        "back to the gather path", ranks=[0])
                    self.attn_backend = "gather"
        if self.paged and self.cfg.scrub_freed_slots:
            # block-granularity scrub: zero each physical block as its last
            # reference drops (the dense pool's whole-row scrub generalized)
            self.pool_mgr._scrub = self._scrub_block
        # chunked prefill: long prompts prefill in fixed-token chunks
        # interleaved with decode steps (bounded co-batched TPOT); each chunk
        # is one suffix-prefill call against the request's partial cache
        self.chunked = bool(self.cfg.chunked_prefill.enabled)
        # disaggregated-fleet role (serving.pools): assigned by the Router
        # via set_pool_role after construction — "mixed" (default), or
        # "prefill"/"decode" with optional per-pool chunk-size override
        # (0 = the shared chunked_prefill.chunk_size)
        self.pool_role = "mixed"
        self.chunk_size_override = 0
        # on-demand block growth (paged only): admission reserves prompt
        # blocks, decode blocks are allocated as cursors advance, and pool
        # exhaustion preempts the newest request back to the queue
        self.growth = self.paged and bool(self.cfg.kv_pool.on_demand_growth)
        self._prefill_jobs = collections.deque()
        self._decode_steps_since_chunk = 1 << 30  # first chunk never waits
        self._admit_seq = 0    # admission order (preemption victim = newest)
        # speculative decoding (serving/speculative.py): a drafter proposes
        # up to k tokens per greedy slot, ONE verify forward checks them,
        # the longest agreeing prefix is accepted. Requires the paged pool
        # (config-validated): rollback rides the block machinery.
        self.spec = bool(self.cfg.speculative.enabled)
        self.spec_k = int(self.cfg.speculative.k)
        self._spec_on = self.spec   # runtime toggle (set_speculation)
        self._drafter = None
        if self.spec:
            from .speculative import build_drafter

            self._drafter = build_drafter(self)
        self.queue = RequestQueue(self.cfg.max_queue_depth)
        self.scheduler = ServingScheduler(
            self.queue, self.n_slots,
            max_prefills_per_step=self.cfg.max_prefills_per_step,
            policy=self.cfg.policy,
            hol_bypass_limit=self.cfg.hol_bypass_limit,
            tenants=self.cfg.tenants if self.cfg.tenants.enabled else None)
        if monitor is None:
            mc = engine.config
            if (mc.tensorboard.enabled or mc.wandb.enabled
                    or mc.csv_monitor.enabled
                    or getattr(mc, "telemetry", None) is not None
                    and mc.telemetry.enabled):
                from ..monitor.monitor import MonitorMaster

                monitor = MonitorMaster(mc)
        self.metrics = ServingMetrics(self.n_slots, self.clock,
                                      monitor=monitor,
                                      interval=self.cfg.monitor_interval,
                                      kv_pool=self._kv_pool_stats
                                      if self.paged else None,
                                      slo=self.cfg.slo)
        # numerics watchdog (the serving leg of telemetry/health.py): the
        # decode program ALWAYS emits the per-slot nonfinite-logit count
        # (so the sanitizer budget audits the real program); the shed hook
        # and Serving/health_* consumers arm on the inference config's
        # health block
        hcfg = getattr(engine.config, "health", None)
        self._health_shed = bool(hcfg is not None and hcfg.enabled)
        # request-lifecycle tracing AGAINST THE SCHEDULER CLOCK: under a
        # virtual clock the trace timestamps are virtual time, which is what
        # makes trace-derived TTFT/TPOT bit-identical to ServingMetrics
        from ..telemetry import SpanTracer

        self.tracer = tracer if tracer is not None else SpanTracer.from_config(
            getattr(engine.config, "telemetry", None), clock=self.clock.now,
            meta={"process": "serving", "n_slots": self.n_slots,
                  "max_len": self.max_len})
        # the structured slo/violation events ride the request tracer
        self.metrics.tracer = self.tracer
        # arms the Serving/spec_* monitor events (coherent with
        # snapshot()["speculative"], the PR 4 trace==metrics discipline)
        self.metrics.speculative_armed = self.spec
        # per-tenant SLO grading reads the class ttft overrides
        if self.cfg.tenants.enabled:
            self.metrics.tenants_cfg = self.cfg.tenants
        # degraded-mode ladder (serving.degraded): the engine-local control
        # loop — submit() consults it for class sheds + token caps, step()
        # drives its evaluation cadence, transitions toggle speculation
        self.degraded_ctl = None
        if self.cfg.degraded.enabled:
            from .control import DegradedModeController

            self.degraded_ctl = DegradedModeController(
                self.cfg.degraded, self.cfg.slo, self.metrics,
                tracer=self.tracer, engine=self)
            self.metrics.degraded = lambda: self.degraded_ctl.level
            self.metrics.degraded_snapshot = self.degraded_ctl.snapshot
        # priority preemption: step()s to skip re-attempting after an
        # eviction freed too few blocks for the interactive candidate
        # (prevents evict/re-admit ping-pong against a tight pool)
        self._pp_cooldown = 0

        self._slots = {}              # slot index -> running Request
        self._free_slots = list(range(self.n_slots - 1, -1, -1))  # pop() -> 0 first
        self._next_id = 0
        self._prefill_programs = OrderedDict()   # padded_len -> jitted prefill
        self._suffix_programs = OrderedDict()    # padded suffix -> jitted
        self._decode_jit = None
        self._insert_jit = None
        self._release_jit = None
        self._sample_first_jit = None
        self._insert_block_jit = None    # paged: copy one block into the pool
        self._seed_cache_jit = None      # paged: block table row -> dense view
        self._scrub_jit = None           # paged: zero one physical block
        self._fresh_cache_jit = None     # chunked: zeroed dense b=1 cache
        self._grow_jit = None            # growth: append one table-row block
        self._verify_jit = None          # speculative: one-forward verify
        self._migrate_in_jit = None      # int8 migration: raw block splice
        # ONE sharding for the pool state, pinned as out_shardings on every
        # pool program: kv heads over the model axis (TP), everything else
        # replicated. Without the pin, insert and decode outputs would carry
        # different inferred shardings and each insert<->decode alternation
        # would recompile — the exact thing the slot pool exists to avoid.
        mesh = engine.mesh
        from ..parallel import MODEL_AXIS

        kvh = engine.module.config.kv_heads
        kv_axis = MODEL_AXIS if kvh % max(engine.mp_world_size, 1) == 0 \
            else None
        self._cache_sharding = NamedSharding(
            mesh, P(None, None, None, kv_axis, None))
        self._rep_sharding = NamedSharding(mesh, P())
        kv_names = ("k", "v", "k_scale", "v_scale") \
            if self.paged and self.cfg.kv_pool.kv_dtype == "int8" \
            else ("k", "v")
        extra = ("table",) if self.paged else ()
        self._state_shardings = {
            name: self._cache_sharding if name in kv_names
            else self._rep_sharding
            for name in kv_names + extra + (
                "pos", "tok", "active", "remaining", "rng", "temp", "top_k",
                "top_p", "eos")}
        self._state = self._init_state()
        if self.paged:
            # the small fix the paged pool makes necessary: the KV window is
            # no longer n_slots x max_len — report the REAL capacity (blocks
            # and tokens) so operators see the effective slot multiplier
            mgr = self.pool_mgr
            cap = mgr.allocatable * mgr.block_size
            log_dist(
                f"ServingEngine: {self.n_slots} slots, paged KV pool "
                f"{mgr.allocatable} blocks x {mgr.block_size} tok = {cap} "
                f"tokens ({cap / self.max_len:.1f} max-len-equivalent slots"
                f", kv_dtype={self.cfg.kv_pool.kv_dtype or 'engine'}, "
                f"attention={self.attn_backend}, "
                f"prefix_cache={'on' if self.cfg.kv_pool.prefix_cache else 'off'}), "
                + (f"speculative={self.cfg.speculative.drafter}/k="
                   f"{self.spec_k}, " if self.spec else "")
                + f"queue depth {self.cfg.max_queue_depth}, "
                f"clock={'virtual' if isinstance(self.clock, VirtualClock) else 'wall'}",
                ranks=[0])
        else:
            log_dist(
                f"ServingEngine: {self.n_slots} slots x {self.max_len} KV window "
                f"(attention={self.attn_backend}), "
                f"queue depth {self.cfg.max_queue_depth}, "
                f"clock={'virtual' if isinstance(self.clock, VirtualClock) else 'wall'}",
                ranks=[0])

    @property
    def chunk_size(self):
        """Effective chunked-prefill chunk size: the per-pool override when
        the Router specialized this replica (serving.pools.*_chunk_size),
        else the shared ``chunked_prefill.chunk_size``."""
        return self.chunk_size_override or self.cfg.chunked_prefill.chunk_size

    def set_pool_role(self, role, chunk_size=0, speculation=""):
        """Assign this replica's disaggregated-pool role (Router-driven,
        ``serving.pools``): records the role for the banner/snapshot,
        applies the per-pool chunk-size override (0 = inherit) and the
        speculation override (""/"on"/"off"). Chunk size only changes the
        SCHEDULE (chunks ride the bucketed suffix programs) and speculation
        toggling never perturbs a seeded stream, so pool specialization
        cannot change any committed token."""
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"unknown pool role {role!r}")
        self.pool_role = role
        self.chunk_size_override = int(chunk_size)
        if speculation:
            self.set_speculation(speculation == "on")
        log_dist(
            f"ServingEngine: pool role {role} "
            f"(chunk_size={self.chunk_size}"
            f"{'*' if self.chunk_size_override else ''}, "
            f"speculation={'on' if self._spec_on else 'off'})", ranks=[0])

    def _kv_pool_stats(self):
        """``KVPoolManager.stats()`` + the active attention backend — the
        kv_pool block every consumer reads (``snapshot()["kv_pool"]``,
        Serving/* events, bench artifacts), so committed numbers always
        record WHICH decode path produced them."""
        st = self.pool_mgr.stats()
        st["attention_backend"] = self.attn_backend
        return st

    # ------------------------------------------------------------------ state
    def _init_state(self):
        cfg = self.engine.module.config
        s = self.n_slots
        if self.paged:
            mgr = self.pool_mgr
            cache = init_paged_cache(cfg, mgr.n_blocks, mgr.block_size,
                                     self.engine.dtype,
                                     self.cfg.kv_pool.kv_dtype or None)
            # every slot starts parked on the garbage block: a dead decode
            # write can never land in an allocatable block
            cache["table"] = jnp.full((s, mgr.blocks_per_slot),
                                      GARBAGE_BLOCK, jnp.int32)
        else:
            cache = init_cache(cfg, s, self.max_len, self.engine.dtype)
        state = dict(cache, **{
            "pos": jnp.zeros((s,), jnp.int32),        # next KV write cursor
            "tok": jnp.zeros((s,), jnp.int32),        # last sampled token
            "active": jnp.zeros((s,), jnp.bool_),
            "remaining": jnp.zeros((s,), jnp.int32),  # decode steps left
            "rng": jnp.zeros((s, 2), jnp.uint32),     # per-slot PRNG keys
            "temp": jnp.zeros((s,), jnp.float32),
            "top_k": jnp.zeros((s,), jnp.int32),
            "top_p": jnp.ones((s,), jnp.float32),
            "eos": jnp.full((s,), -1, jnp.int32),     # -1 = no eos
        })
        return {name: jax.device_put(a, self._state_shardings[name])
                for name, a in state.items()}

    # -------------------------------------------------------------- programs
    def _prefill_program(self, padded_len):
        """One compiled prefill per prompt bucket (same LRU bound as the
        engine's generate cache)."""
        model, max_len, dtype = self.engine.module, self.max_len, self.engine.dtype

        def build():
            def prefill(params, ids, true_len):
                c = init_cache(model.config, 1, max_len, dtype)
                logits, c = forward_with_cache(model, params, ids, c, 0,
                                               max_len, prefill=True)
                last = jax.lax.dynamic_slice_in_dim(
                    logits, true_len - 1, 1, axis=1)[:, 0]
                return last, c

            with self.engine.mesh:
                return jax.jit(prefill, out_shardings=(
                    self._rep_sharding,
                    {"k": self._cache_sharding, "v": self._cache_sharding}))

        return lru_compiled(self._prefill_programs, padded_len, build,
                            int(self.engine.config.compile_cache_size or 0),
                            "serving prefill")

    def _suffix_program(self, padded_len):
        """Shared-prefix hit: prefill only the SUFFIX (cache already holds
        the prefix KV gathered from shared blocks) — one compiled program
        per suffix bucket, start position and true length traced."""
        model, max_len = self.engine.module, self.max_len

        def build():
            def suffix_prefill(params, ids, cache, start_pos, true_len):
                logits, c = forward_with_cache(model, params, ids, cache,
                                               start_pos, max_len)
                last = jax.lax.dynamic_slice_in_dim(
                    logits, true_len - 1, 1, axis=1)[:, 0]
                return last, c

            with self.engine.mesh:
                return jax.jit(suffix_prefill, donate_argnums=(2,),
                               out_shardings=(
                                   self._rep_sharding,
                                   {"k": self._cache_sharding,
                                    "v": self._cache_sharding}))

        return lru_compiled(self._suffix_programs, padded_len, build,
                            int(self.engine.config.compile_cache_size or 0),
                            "serving suffix prefill")

    def _build_pool_programs(self):
        model, max_len = self.engine.module, self.max_len
        paged = self.paged
        attn_backend = self.attn_backend
        bs = self.pool_mgr.block_size if paged else 0
        pool_keys = ("k", "v", "k_scale", "v_scale") \
            if paged and self.cfg.kv_pool.kv_dtype == "int8" else ("k", "v")

        def decode(params, state):
            # one token for EVERY slot, each at its own cursor; inactive
            # slots decode garbage into their own freed rows (dense: the
            # slot's private rows, overwritten whole-row by the next insert;
            # paged: the reserved garbage block their table row points at)
            # and are masked below
            split = jax.vmap(jax.random.split)(state["rng"])  # [S, 2, 2]
            if paged:
                logits, cache = forward_with_paged_cache(
                    model, params, state["tok"][:, None],
                    {k: state[k] for k in pool_keys}, state["table"],
                    state["pos"], bs, attention_backend=attn_backend)
            else:
                logits, cache = forward_with_cache(
                    model, params, state["tok"][:, None],
                    {"k": state["k"], "v": state["v"]}, state["pos"], max_len)
            # in-graph health: per-slot nonfinite-logit count (the serving
            # leg of the numerics flight recorder — one tiny i32[S] side
            # output, no host callback; the sanitizer budget audits it)
            nonfinite = jnp.sum(
                jnp.logical_not(jnp.isfinite(logits[:, 0])),
                axis=-1).astype(jnp.int32)
            nxt = sample_token(logits[:, 0], split[:, 0],
                               temperature=state["temp"],
                               top_k=state["top_k"], top_p=state["top_p"])
            active = state["active"]
            nxt = jnp.where(active, nxt, state["tok"])
            remaining = state["remaining"] - active.astype(jnp.int32)
            hit_eos = (state["eos"] >= 0) & (nxt == state["eos"])
            done_now = active & (hit_eos | (remaining <= 0))
            new_state = dict(cache, **{
                "pos": state["pos"] + active.astype(jnp.int32),
                "tok": nxt,
                "active": active & jnp.logical_not(done_now),
                "remaining": remaining,
                "rng": split[:, 1],
                "temp": state["temp"], "top_k": state["top_k"],
                "top_p": state["top_p"], "eos": state["eos"],
            })
            if paged:
                new_state["table"] = state["table"]
            return (nxt, done_now, nonfinite), new_state

        def verify(params, state, drafts, draft_len):
            # speculative decoding's ONE target forward: k+1 positions per
            # slot — row 0 is the decode every active slot was owed, rows
            # 1..k check the drafts. Greedy acceptance, cursor advance,
            # remaining/eos bookkeeping all happen IN-GRAPH, so a verify
            # step is exactly one dispatch (the program the serving-verify
            # sanitizer budget audits) and the rng splits exactly once —
            # a co-batched sampled slot cannot tell verify from decode.
            split = jax.vmap(jax.random.split)(state["rng"])
            ids = jnp.concatenate([state["tok"][:, None], drafts], axis=1)
            logits, cache = verify_with_paged_cache(
                model, params, ids, {k: state[k] for k in pool_keys},
                state["table"], state["pos"], bs, draft_len)
            active = state["active"]
            kk = drafts.shape[1]
            # column 0 samples with the slot's key (greedy rows are exact
            # argmax inside sample_token); columns 1..k are greedy targets
            # — only greedy rows ever carry drafts (engine eligibility)
            first = sample_token(logits[:, 0], split[:, 0],
                                 temperature=state["temp"],
                                 top_k=state["top_k"], top_p=state["top_p"])
            tgt = jnp.argmax(logits.astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            out_toks = jnp.concatenate([first[:, None], tgt[:, 1:]], axis=1)
            # accept the longest prefix where draft == target argmax
            matches = (drafts == out_toks[:, :kk]) \
                & (jnp.arange(kk)[None, :] < draft_len[:, None])
            accepted = jnp.sum(
                jnp.cumprod(matches.astype(jnp.int32), axis=1), axis=1)
            # emit candidate j while j <= accepted, tokens are still owed,
            # and no earlier emitted token hit eos
            js = jnp.arange(kk + 1)[None, :]
            remaining = state["remaining"]
            cand = (js <= accepted[:, None]) & (js < remaining[:, None])
            is_eos = (state["eos"][:, None] >= 0) \
                & (out_toks == state["eos"][:, None])
            hit = (cand & is_eos).astype(jnp.int32)
            eos_before = (jnp.cumsum(hit, axis=1) - hit) > 0
            emit = cand & jnp.logical_not(eos_before) & active[:, None]
            n_emit = jnp.sum(emit.astype(jnp.int32), axis=1)
            # in-graph health guard over the EMITTED logit rows only (freed
            # slots decode garbage by design; rejected rows never stream)
            nonfinite = jnp.sum(
                jnp.logical_not(jnp.isfinite(logits)) & emit[:, :, None],
                axis=(1, 2)).astype(jnp.int32)
            new_tok = jnp.take_along_axis(
                out_toks, jnp.clip(n_emit - 1, 0, kk)[:, None], axis=1)[:, 0]
            new_tok = jnp.where(n_emit > 0, new_tok, state["tok"])
            remaining = remaining - n_emit
            hit_eos = jnp.any(emit & is_eos, axis=1)
            done_now = active & (hit_eos | (remaining <= 0))
            new_state = dict(cache, **{
                "table": state["table"],
                "pos": state["pos"] + n_emit,
                "tok": new_tok,
                "active": active & jnp.logical_not(done_now),
                "remaining": remaining,
                "rng": split[:, 1],
                "temp": state["temp"], "top_k": state["top_k"],
                "top_p": state["top_p"], "eos": state["eos"],
            })
            return (out_toks, n_emit, accepted, done_now,
                    nonfinite), new_state

        def insert(state, slot, k_slot, v_slot, tok, pos, remaining, rng,
                   temp, top_k, top_p, eos):
            # slot index is TRACED: one compiled insert covers every slot
            kv = insert_slot_kv({"k": state["k"], "v": state["v"]},
                                {"k": k_slot, "v": v_slot}, slot)
            put = lambda a, v_: a.at[slot].set(v_)
            return {
                "k": kv["k"], "v": kv["v"],
                "pos": put(state["pos"], pos),
                "tok": put(state["tok"], tok),
                "active": put(state["active"], True),
                "remaining": put(state["remaining"], remaining),
                "rng": state["rng"].at[slot].set(rng),
                "temp": put(state["temp"], temp),
                "top_k": put(state["top_k"], top_k),
                "top_p": put(state["top_p"], top_p),
                "eos": put(state["eos"], eos),
            }

        def insert_meta(state, slot, table_row, tok, pos, remaining, rng,
                        temp, top_k, top_p, eos):
            # paged: the KV rows were already copied block-wise
            # (insert_block); this binds the slot's block table + scalars
            put = lambda a, v_: a.at[slot].set(v_)
            return dict(state, **{
                "table": state["table"].at[slot].set(table_row),
                "pos": put(state["pos"], pos),
                "tok": put(state["tok"], tok),
                "active": put(state["active"], True),
                "remaining": put(state["remaining"], remaining),
                "rng": state["rng"].at[slot].set(rng),
                "temp": put(state["temp"], temp),
                "top_k": put(state["top_k"], top_k),
                "top_p": put(state["top_p"], top_p),
                "eos": put(state["eos"], eos),
            })

        def insert_blocks(state, dense_k, dense_v, block_ids, src_starts):
            # copy a request's private blocks from its freshly-prefilled
            # dense cache into the pool in ONE dispatch: a fori_loop over
            # the (traced) padded [blocks_per_slot] id/offset arrays, so
            # TTFT pays one jitted call instead of one per block. Padding
            # entries point at the garbage block (their copy is dead) —
            # total device work is O(max_len), the dense insert's cost.
            pool = {k: state[k] for k in pool_keys}

            def body(i, p):
                return insert_block_kv(p, {"k": dense_k, "v": dense_v},
                                       block_ids[i], src_starts[i], bs)

            pool = jax.lax.fori_loop(0, block_ids.shape[0], body, pool)
            return dict(state, **pool)

        def seed_cache(state, table_row):
            # shared-prefix hit: materialize the slot's dense cache view
            # from its (partly shared) block row for the suffix prefill
            return gather_slot_cache(model.config,
                                     {k: state[k] for k in pool_keys},
                                     table_row, self.engine.dtype)

        def fresh_cache():
            # chunked prefill / preemption resume: the request carries a
            # dense b=1 cache ACROSS scheduler steps, so it starts from an
            # explicit zeroed one instead of one built inside the prefill
            # program (the suffix programs donate and return it per chunk)
            return init_cache(model.config, 1, max_len, self.engine.dtype)

        def grow(state, slot, j, block_id):
            # on-demand growth: extend a running slot's KV coverage by one
            # block — table[slot, j] retargets from the garbage block to the
            # freshly-allocated one (slot/j/block_id traced: compiles once)
            return dict(state,
                        table=state["table"].at[slot, j].set(block_id))

        def release(state, slot):
            if paged:
                # MANDATORY on the paged pool (not hygiene): the freed
                # slot's blocks go back to the allocator, so its table row
                # must retreat to the garbage block before anything reuses
                # them — a dead decode write to a reallocated block would
                # be silent cross-request corruption
                return dict(
                    state,
                    table=state["table"].at[slot].set(
                        jnp.full((state["table"].shape[1],), GARBAGE_BLOCK,
                                 jnp.int32)),
                    pos=state["pos"].at[slot].set(0),
                    active=state["active"].at[slot].set(False))
            # hygiene scrub (config.scrub_freed_slots): zero the freed KV
            # rows; the causal mask + whole-row insert already guarantee no
            # stale-KV leak without it
            kv = reset_slot_kv({"k": state["k"], "v": state["v"]}, slot)
            return dict(state, k=kv["k"], v=kv["v"],
                        active=state["active"].at[slot].set(False))

        def scrub_block(state, block_id):
            # block-granularity scrub (scrub_freed_slots under paging):
            # zero a physical block when its last reference drops
            return dict(state, **reset_block_kv(
                {k: state[k] for k in pool_keys}, block_id))

        def migrate_in(state, raw_blocks, block_ids):
            # live KV migration splice for int8 pools: copy a migrated
            # request's RAW physical blocks — payload AND scales — into
            # freshly-allocated pool blocks in ONE dispatch (the fori_loop
            # mirror of insert_blocks; padding ids point at the garbage
            # block, so their copy is dead). Raw, never dequantized: a
            # dequant -> requant round trip can perturb the recomputed
            # scale in its last ulp (see serving/migration.py). Non-int8
            # pools migrate through the EXISTING insert_blocks program —
            # their dense view IS the raw bytes.
            pool = {k: state[k] for k in pool_keys}

            def body(i, p):
                return inject_block_kv(p, raw_blocks, block_ids[i], i)

            pool = jax.lax.fori_loop(0, block_ids.shape[0], body, pool)
            return dict(state, **pool)

        def sample_first(logits, key, temp, top_k, top_p):
            # same in-graph guard as decode: the first token samples from
            # prefill logits, which must never stream unchecked
            nonfinite = jnp.sum(
                jnp.logical_not(jnp.isfinite(logits))).astype(jnp.int32)
            tok = sample_token(logits, key[None, :],
                               temperature=jnp.reshape(temp, (1,)),
                               top_k=jnp.reshape(top_k, (1,)),
                               top_p=jnp.reshape(top_p, (1,)))
            return tok, nonfinite

        rep, st = self._rep_sharding, self._state_shardings
        with self.engine.mesh:
            self._decode_jit = jax.jit(decode, donate_argnums=(1,),
                                       out_shardings=((rep, rep, rep), st))
            if paged:
                self._insert_jit = jax.jit(insert_meta, donate_argnums=(0,),
                                           out_shardings=st)
                self._insert_block_jit = jax.jit(
                    insert_blocks, donate_argnums=(0,), out_shardings=st)
                self._seed_cache_jit = jax.jit(
                    seed_cache, out_shardings={"k": self._cache_sharding,
                                               "v": self._cache_sharding})
                self._scrub_jit = jax.jit(scrub_block, donate_argnums=(0,),
                                          out_shardings=st)
                if self.growth:
                    self._grow_jit = jax.jit(grow, donate_argnums=(0,),
                                             out_shardings=st)
                if self.spec:
                    self._verify_jit = jax.jit(
                        verify, donate_argnums=(1,),
                        out_shardings=((rep, rep, rep, rep, rep), st))
                if self.cfg.kv_pool.kv_dtype == "int8":
                    self._migrate_in_jit = jax.jit(
                        migrate_in, donate_argnums=(0,), out_shardings=st)
            else:
                self._insert_jit = jax.jit(insert, donate_argnums=(0,),
                                           out_shardings=st)
            self._fresh_cache_jit = jax.jit(
                fresh_cache, out_shardings={"k": self._cache_sharding,
                                            "v": self._cache_sharding})
            self._release_jit = jax.jit(release, donate_argnums=(0,),
                                        out_shardings=st)
            self._sample_first_jit = jax.jit(sample_first,
                                             out_shardings=(rep, rep))

    def trace_decode(self):
        """``(lowered, jaxpr-or-None)`` of the decode program over the live
        slot pool — the entry point for the static sanitizer /
        ``tools/program_lint.py``. ONE trace serves both views (tracing only
        builds avals: nothing executes, and the donation annotations ride
        along for the audit); jax versions without ``jit(...).trace`` fall
        back to ``lower()`` and a None jaxpr."""
        if self._decode_jit is None:
            self._build_pool_programs()
        trace = getattr(self._decode_jit, "trace", None)
        if trace is not None:
            t = trace(self.engine.params, self._state)
            return t.lower(), t.jaxpr
        return self._decode_jit.lower(self.engine.params, self._state), None

    def lower_decode(self):
        """The lowered (uncompiled) decode program (see ``trace_decode``)."""
        return self.trace_decode()[0]

    def trace_prefill_chunk(self, chunk_tokens=None):
        """``(lowered, jaxpr-or-None)`` of the chunked suffix-prefill program
        (one full chunk's bucket) — the ``program_lint --program
        prefill-chunked`` entry point, mirroring ``trace_decode``. This is
        the SAME compiled program a chunk dispatches (and a shared-prefix
        suffix hit shares): q-block written at a traced start position
        against a donated, partially-filled dense b=1 cache."""
        if self._decode_jit is None:
            self._build_pool_programs()
        chunk = int(chunk_tokens or self.chunk_size)
        padded = self.engine._bucket_prompt_len(min(chunk, self.max_len),
                                                self.max_len)
        fn = self._suffix_program(padded)
        cache = init_cache(self.engine.module.config, 1, self.max_len,
                           self.engine.dtype)
        args = (self.engine.params, jnp.zeros((1, padded), jnp.int32), cache,
                np.int32(0), np.int32(min(chunk, padded)))
        trace = getattr(fn, "trace", None)
        if trace is not None:
            t = trace(*args)
            return t.lower(), t.jaxpr
        return fn.lower(*args), None

    def trace_verify(self, spec_k=None):
        """``(lowered, jaxpr-or-None)`` of the speculative verify program —
        the ``program_lint --program verify`` entry point, mirroring
        ``trace_decode``. Traces the SAME jitted closure a verify step
        dispatches: k+1 positions per slot against the donated paged pool
        state, with the draft matrix and per-slot draft lengths traced (one
        compiled program per k)."""
        if not self.spec:
            raise ConfigError(
                "trace_verify: serving.speculative is not enabled")
        if self._decode_jit is None:
            self._build_pool_programs()
        kk = int(spec_k or self.spec_k)
        args = (self.engine.params, self._state,
                jnp.zeros((self.n_slots, kk), jnp.int32),
                jnp.zeros((self.n_slots,), jnp.int32))
        trace = getattr(self._verify_jit, "trace", None)
        if trace is not None:
            t = trace(*args)
            return t.lower(), t.jaxpr
        return self._verify_jit.lower(*args), None

    def compile_counts(self):
        """Compiled-program census, pinned by the tier-1 no-recompile test:
        the decode step compiles exactly once per (model, slot-pool)
        configuration no matter how requests join/leave mid-flight."""
        size = lambda f: f._cache_size() if f is not None else 0
        out = {
            "decode": size(self._decode_jit),
            "insert": size(self._insert_jit),
            "prefill_buckets": len(self._prefill_programs),
        }
        if self.paged:
            out["insert_block"] = size(self._insert_block_jit)
            out["seed_cache"] = size(self._seed_cache_jit)
            if self.cfg.kv_pool.kv_dtype == "int8":
                out["migrate_in"] = size(self._migrate_in_jit)
        if self.paged or self.chunked or self.growth:
            out["suffix_buckets"] = len(self._suffix_programs)
        if self.growth:
            out["grow"] = size(self._grow_jit)
        if self.spec:
            out["verify"] = size(self._verify_jit)
            out.update(self._drafter.compile_counts())
        return out

    def _scrub_block(self, block_id):
        """KVPoolManager scrub hook: zero one freed physical block."""
        if self._scrub_jit is not None and self._state is not None:
            self._state = self._scrub_jit(self._state, np.int32(block_id))

    # ------------------------------------------------------------ submission
    def submit(self, request, **kwargs):
        """Admit a request into the bounded queue (or shed it with a reason).

        ``request``: Request | dict | token array (kwargs become Request
        fields for the array form). Returns the Request; check ``.state`` —
        REJECTED means admission control shed it (``.reject_reason`` in
        {queue_full, prompt_too_long, bad_request})."""
        if kwargs and not isinstance(request, (Request, dict)):
            req = Request(prompt=np.asarray(request), **kwargs)
        else:
            req = as_request(request)
        if req.request_id is None:
            req.request_id = self._next_id
            self._next_id += 1
        if req.trace_id is None:
            # a Router stamps its own fleet-global trace id before this;
            # the standalone engine mints one so single-replica traces are
            # mergeable by the same machinery
            req.trace_id = f"req-{req.request_id:06d}"
        req.submit_time = self.clock.now()
        if req.arrival_time is not None and not req.arrival_resolved:
            # direct submit(): arrival_time is an offset from now (same
            # contract as serve()); without this, ttft would subtract a raw
            # offset from an absolute clock reading
            req.arrival_time += req.submit_time
            req.arrival_resolved = True
        reason = None
        if self.degraded_ctl is not None and not req.tokens:
            # degraded-mode admission policy (fresh submissions only — a
            # resumed/migrated stream is committed work, never shed here):
            # rung >= 1 sheds batch, only the LAST rung sheds interactive;
            # rung >= 2 caps the generation budget of what it still admits
            if self.degraded_ctl.sheds_class(req.tenant_class):
                reason = REJECT_DEGRADED
                req.state = RequestState.REJECTED
                req.reject_reason = reason
                self.queue.shed_counts[reason] += 1
            else:
                cap = self.degraded_ctl.token_cap()
                if cap and req.max_new_tokens > cap:
                    req.max_new_tokens = cap
        if reason is None:
            reason = self.queue.admit(
                req, self.max_len,
                kv_fits=self.pool_mgr.fits_ever if self.paged else None)
        if reason is None:
            self.metrics.record_submit(req)
            self.tracer.instant(
                "request/queued", cat="serving", request_id=req.request_id,
                trace_id=req.trace_id, prompt_len=req.prompt_len,
                tenant_id=req.tenant_id, tenant_class=req.tenant_class,
                # TTFT's zero point, exactly as Request.ttft defines it
                start=req.start_time)
        else:
            self.metrics.record_shed(reason, req)
            self.tracer.instant("request/shed", cat="serving",
                                request_id=req.request_id,
                                trace_id=req.trace_id, reason=reason,
                                tenant_id=req.tenant_id,
                                tenant_class=req.tenant_class)
        return req

    # ------------------------------------------------------------- the loop
    def step(self):
        """One scheduler iteration: admit queued requests into free slots,
        advance at most one pending prefill chunk (chunked prefill), grow or
        preempt paged slots whose cursor reached the end of their blocks
        (on-demand growth), then run one decode step over the pool. Returns
        the list of TokenEvents produced."""
        events = []
        can_admit = self._make_can_admit() if self.paged else None
        admitted = self._maybe_priority_preempt(can_admit)
        if admitted is None:
            admitted = self.scheduler.next_admissions(len(self._free_slots),
                                                      self.clock.now(),
                                                      can_admit=can_admit)
        for req in admitted:
            self._start_request(req, events)
        if self._prefill_jobs and self._chunk_due():
            self._advance_prefill(events)
        if self.growth and self._slots:
            self._grow_or_preempt()
        if self._slots:
            drafts = self._collect_drafts() \
                if (self.spec and self._spec_on) else None
            if drafts:
                self._verify_once(events, drafts)
            else:
                self._decode_once(events)
            self._decode_steps_since_chunk += 1
            if self.paged and self._slots and self.cfg.migration.enabled \
                    and self.cfg.migration.snapshot_interval_tokens > 0:
                self._maybe_snapshot()
        elif not admitted and not self._prefill_jobs and self.queue.depth:
            # nothing running and the queue head hasn't arrived yet (direct
            # submit with a future arrival offset): idle the clock forward to
            # it, or a virtual-clock step() loop would spin forever
            head = self.queue.peek()
            if head.arrival_time is not None:
                gap = head.arrival_time - self.clock.now()
                if gap > 0:
                    self.clock.sleep(gap)
        if self.degraded_ctl is not None:
            self.degraded_ctl.observe(self.clock.now())
        self.metrics.observe_step(self.queue.depth, len(self._slots))
        return events

    def _maybe_priority_preempt(self, can_admit):
        """Priority preemption (serving.tenants.preempt): when every slot
        is busy and an arrived INTERACTIVE request waits, evict the
        newest-admitted BATCH stream through the rollback-safe preempt
        machinery (rng captured, blocks released — it resumes bitwise-
        identically later) and admit the interactive request DIRECTLY into
        the freed capacity, returning the admission list for this step.
        Direct admission is load-bearing: ``_preempt`` re-queues the
        victim at the HEAD (it outranks every queued arrival by original
        admission order), so routing the step through ``next_admissions``
        would hand the freed slot straight back to the victim — an
        evict/re-admit livelock instead of a priority grant. Returns None
        when no preemption applies (the normal admission path runs).
        Paged pools only: ``_preempt`` is block-machinery-coupled."""
        tcfg = self.cfg.tenants
        if not (tcfg.enabled and tcfg.preempt and self.paged) \
                or self._free_slots or not self.queue.depth:
            return None
        if self._pp_cooldown > 0:
            self._pp_cooldown -= 1
            return None
        now = self.clock.now()
        cand_i = None
        for i in range(self.queue.depth):
            r = self.queue.peek_at(i)
            if r.arrival_time is not None and r.arrival_time > now:
                break  # arrivals are time-ordered; nothing further is due
            if r.admit_time is not None:
                continue  # a preemption returner resumes the normal way
            if r.tenant_class == CLASS_INTERACTIVE \
                    and self.scheduler.budget_ok(r, now):
                cand_i = i
                break
        if cand_i is None:
            return None
        batch_slots = [s for s, r_ in self._slots.items()
                       if r_.tenant_class == CLASS_BATCH]
        if not batch_slots:
            return None  # nothing evictable: classes never evict their own
        victim_slot = max(batch_slots,
                          key=lambda s_: self._slots[s_].admit_seq)
        victim = self._slots[victim_slot]
        self._preempt(victim_slot)
        victim.priority_evictions += 1
        self.metrics.priority_evictions += 1
        self.tracer.instant("request/priority_evicted", cat="serving",
                            ts=self.clock.now(),
                            request_id=victim.request_id,
                            trace_id=victim.trace_id,
                            tenant_id=victim.tenant_id,
                            n_tokens=len(victim.tokens))
        # the victim's push_front shifted the candidate one slot back
        cand = self.queue.peek_at(cand_i + 1)
        if can_admit is not None and not can_admit(cand):
            # the eviction freed too few blocks (large prompt vs short
            # victim): leave the candidate queued and back off — retrying
            # every step would churn evictions without ever admitting
            self._pp_cooldown = 8
            return []
        cand = self.queue.pop_at(cand_i + 1)
        self.scheduler.charge(cand, now)  # fair-share + budget accounting
        return [cand]

    def _make_can_admit(self):
        """Block-aware admission predicate for the scheduler. The queue head
        waits until enough blocks are free, evictable, or unreserved; a
        granted admission RESERVES its blocks in the pool manager (not a
        step-local counter: chunked prefill opens a multi-step window
        between admission and slot insert, and growth/later admissions must
        not steal the head's blocks meanwhile). Prefix sharing is ignored
        here (a hit only needs FEWER blocks, so the check stays sound).
        No livelock: every queued request passed fits_ever at submit, and
        with no slots running every non-free block is prefix-cache-evictable
        and every reservation is consumed by a job already holding a slot,
        so the head always admits once running requests drain."""
        def can_admit(req):
            if self.growth:
                # reserve-as-you-decode: admission pays only the prefilled
                # positions (prompt, or prompt + replayed tokens on resume)
                # PLUS the first decode write — see _growth_admission_len
                need = self.pool_mgr.blocks_for_prefill(
                    self._growth_admission_len(req))
            else:
                need = self.pool_mgr.blocks_for(req.prompt_len,
                                                req.max_new_tokens)
            if not self.pool_mgr.can_allocate(need):
                return False
            self.pool_mgr.reserve(need)
            req.reserved_blocks = need
            return True

        return can_admit

    @staticmethod
    def _prefill_len(req):
        """Positions the request's prefill writes: the prompt, plus — on a
        preemption resume — every already-generated token except the last
        (which decode re-feeds at the cursor)."""
        return req.prompt_len + max(len(req.tokens) - 1, 0)

    def _growth_admission_len(self, req):
        """Positions a growth-mode admission must cover: the prefill PLUS
        the first decode write (at position ``prefill_len``) whenever the
        request will decode at all. Sizing only the prefill is a LIVELOCK:
        a resumed request re-enters exactly at a block boundary, so it
        must grow before producing a single token — and with the queue
        head's admission reservation holding the pool's last blocks, the
        grow fails, the request preempts itself, and the two ping-pong
        forever with zero progress (caught by the fleet-observability
        preemption workload, tier-1-pinned in test_fleet_obs). Covering
        the first write restores the progress guarantee: every admission
        nets at least one token before any preemption."""
        will_decode = bool(req.tokens) or req.max_new_tokens > 1
        return self._prefill_len(req) + (1 if will_decode else 0)

    def _unreserve(self, req):
        """Cancel an admission-time block reservation (early finish / shed
        paths that never reach the slot insert)."""
        if req.reserved_blocks:
            self.pool_mgr.consume_reservation(req.reserved_blocks)
            req.reserved_blocks = 0

    def _chunk_due(self):
        """A pending prefill chunk runs when nothing is decoding, when
        chunking is off (preemption-resume jobs complete in one shot), or
        once the configured decode steps have run since the last chunk.
        The chunk SIZE bounds the co-batched worst inter-token gap (one
        chunk at most between two decode steps); this pacing knob trades
        the long prompt's prefill completion for decode throughput."""
        if not self.chunked or not self._slots:
            return True
        return (self._decode_steps_since_chunk
                >= self.cfg.chunked_prefill.decode_steps_between_chunks)

    def _request_key(self, req):
        if req.sampling.seed is not None:
            base = jax.random.PRNGKey(int(req.sampling.seed))
        else:
            base = jax.random.fold_in(self.engine._rng, req.request_id)
        return jax.random.split(base)  # [2, 2]: (first-token key, slot chain)

    def _start_request(self, req, events):
        if self._decode_jit is None:
            self._build_pool_programs()
        resume = bool(req.tokens)  # preempted request rejoining from the queue
        if resume and len(req.tokens) > 1:
            # replay prefill: prompt + every generated token except the last
            # (decode re-feeds it at the cursor) — rebuilding exactly the KV
            # coverage the preemption released, so the stream continues
            # bitwise-identically
            ids_full = np.concatenate(
                [req.prompt, np.asarray(req.tokens[:-1], np.int32)])
        else:
            ids_full = req.prompt
        if req.prefill_start_time is None:
            # queue-wait window closes at the FIRST slot grant (a resume
            # replay keeps the original endpoint — its wait was decided when
            # it first left the queue)
            req.prefill_start_time = self.clock.now()
            self.metrics.record_queue_wait(req)
        shared_len, shared_blocks = 0, []
        if self.paged:
            # take refs on matched prefix blocks NOW so an eviction between
            # here and the slot insert can't dangle them
            shared_len, shared_blocks = self.pool_mgr.acquire_prefix(ids_full)
        if shared_len and not resume:
            # positions the prefix-cache hit never dispatches: reported in
            # the goodput block (work avoided, not part of the frac)
            req.prefix_saved_tokens += shared_len
            self.metrics.prefix_saved_tokens += shared_len
        if resume and self.paged and req.migration is not None \
                and self.cfg.migration.enabled \
                and req.migration.compatible_with(self._pool_geometry()) \
                and self._splice_snapshot(req, req.migration, ids_full,
                                          shared_len, shared_blocks):
            # live KV migration: the snapshot spliced (fresh: straight back
            # into the decode pool; stale: full blocks landed, only the
            # tail replays) — the normal replay path below never runs
            return
        if req.handoff_pending:
            # the handoff splice degraded to a replay-resume (snapshot
            # incompatible here, or fully covered by this pool's prefix
            # cache): the stream still completed its move
            req.handoff_pending = False
            req.handoffs += 1
        chunk = self.chunk_size
        if resume or (self.chunked and len(ids_full) - shared_len > chunk):
            # multi-step prefill (chunked and/or resume replay): reserve the
            # slot now, seed the partial cache, and let the step loop drive
            # chunks interleaved with decode steps (_advance_prefill)
            slot = self._free_slots.pop()
            if shared_len:
                mgr = self.pool_mgr
                row = np.full((mgr.blocks_per_slot,), GARBAGE_BLOCK, np.int32)
                row[:len(shared_blocks)] = shared_blocks
                cache = self._seed_cache_jit(self._state, jnp.asarray(row))
            else:
                cache = self._fresh_cache_jit()
            self._prefill_jobs.append(_PrefillJob(
                req=req, slot=slot, cache=cache,
                ids=np.asarray(ids_full, np.int32), pos=shared_len,
                shared_len=shared_len, shared_blocks=shared_blocks,
                resume=resume))
            return
        if shared_len:
            # shared-prefix hit: the pool already holds the prefix KV — seed
            # a dense view from the (partly shared) block row and prefill
            # ONLY the suffix. Capped at prompt_len - 1, so there is always
            # at least one suffix token to yield the first-token logits.
            mgr = self.pool_mgr
            row = np.full((mgr.blocks_per_slot,), GARBAGE_BLOCK, np.int32)
            row[:len(shared_blocks)] = shared_blocks
            suffix = req.prompt[shared_len:]
            # ceiling shrinks by the shared prefix: the suffix q-block is
            # written AT pos=shared_len, and a bucket that overruns max_len
            # would make XLA clamp the update start — silently clobbering
            # the prefix KV rows (bucket 64 + shared 16 in a 64 window did
            # exactly that before this cap)
            padded = self.engine._bucket_prompt_len(
                len(suffix), self.max_len - shared_len)
            req.padding_tokens += padded - len(suffix)
            self.metrics.record_prefill_work(padded, len(suffix))
            with self.tracer.span("prefill", cat="serving",
                                  request_id=req.request_id,
                                  trace_id=req.trace_id, n=len(suffix),
                                  padded_len=padded, shared_len=shared_len):
                cache = self._seed_cache_jit(self._state, jnp.asarray(row))
                ids = np.zeros((1, padded), np.int32)
                ids[0, :len(suffix)] = suffix
                logits, cache = self._suffix_program(padded)(
                    self.engine.params, jnp.asarray(ids), cache,
                    np.int32(shared_len), np.int32(len(suffix)))
                # the prefix-cache win in virtual time: only the suffix pays
                self.clock.advance(
                    padded * self.cfg.virtual_prefill_cost_per_token)
        else:
            # ceiling is the full slot window: pad rows past the cursor are
            # causally masked and then overwritten one-by-one as decode
            # advances (same scheme as generate()), so padding may overlap
            # the generation region — one bucket serves every max_new_tokens
            padded = self.engine._bucket_prompt_len(req.prompt_len,
                                                    self.max_len)
            req.padding_tokens += padded - req.prompt_len
            self.metrics.record_prefill_work(padded, req.prompt_len)
            with self.tracer.span("prefill", cat="serving",
                                  request_id=req.request_id,
                                  trace_id=req.trace_id, n=req.prompt_len,
                                  padded_len=padded):
                ids = np.zeros((1, padded), np.int32)
                ids[0, :req.prompt_len] = req.prompt
                logits, cache = self._prefill_program(padded)(
                    self.engine.params, jnp.asarray(ids),
                    np.int32(req.prompt_len))
                self.clock.advance(
                    padded * self.cfg.virtual_prefill_cost_per_token)

        self._after_prefill(req, cache, shared_len, shared_blocks, logits,
                            events)

    def _after_prefill(self, req, cache, shared_len, shared_blocks, logits,
                       events, slot=None):
        """Sample the first token from the prefill logits (in-graph health
        guard included) and either finish the request immediately or bind a
        slot. ``slot`` is the job-reserved slot for chunked prefills (freed
        back on an early finish); single-shot prefills pop one here."""
        keys = self._request_key(req)
        s = req.sampling
        tok, nf = self._sample_first_jit(
            logits, keys[0], np.float32(s.temperature),
            np.int32(s.top_k), np.float32(s.top_p))
        now = self.clock.now()
        nf = int(nf)
        if nf:
            # symmetric with decode: the counter reports whether or not the
            # shed hook is armed
            self.metrics.record_health_step(1)
        if self._health_shed and nf:
            # poisoned prefill: the first token is garbage — shed BEFORE
            # streaming anything (the request never takes a slot)
            if self.paged:
                self.pool_mgr.release_blocks(shared_blocks)
                self._unreserve(req)
            if slot is not None:
                self._free_slots.append(slot)
            self.metrics.record_shed("unhealthy_slot")
            self.metrics.record_unhealthy()
            self.tracer.instant("request/unhealthy", cat="serving", ts=now,
                                request_id=req.request_id,
                                trace_id=req.trace_id,
                                nonfinite_logits=int(nf))
            self._finish(req, FINISH_UNHEALTHY, now)
            events.append(TokenEvent(req.request_id, -1, 0, True,
                                     FINISH_UNHEALTHY, now))
            return
        t = int(np.asarray(tok)[0])
        req.state = RequestState.RUNNING
        req.first_token_time = now
        req.tokens.append(t)
        self.metrics.record_tokens(1, req)
        self.metrics.record_first_token(req)
        self.tracer.instant("request/first_token", cat="serving", ts=now,
                            request_id=req.request_id,
                            trace_id=req.trace_id)

        eos = req.eos_token_id
        if (eos is not None and t == eos) or t in req.stop_token_ids \
                or req.max_new_tokens == 1:
            if eos is not None and t == eos:
                reason = FINISH_EOS
            elif t in req.stop_token_ids:
                reason = FINISH_STOP
            else:
                reason = FINISH_LENGTH
            if self.paged:
                # finished at the first token: no blocks were bound
                self.pool_mgr.release_blocks(shared_blocks)
                self._unreserve(req)
            if slot is not None:
                self._free_slots.append(slot)
            self._finish(req, reason, now)
            events.append(TokenEvent(req.request_id, t, 0, True, reason, now))
            return
        if slot is None:
            slot = self._free_slots.pop()
        self._slots[slot] = req
        req.slot = slot
        if req.admit_seq < 0:
            # preemption-victim ordering: newest admission yields first; a
            # RESUMED request keeps its original seniority
            req.admit_seq = self._admit_seq
            self._admit_seq += 1
        if self.paged:
            self._insert_paged(req, slot, cache, shared_len, shared_blocks,
                               tok[0], keys[1], s, eos,
                               req.max_new_tokens - 1)
        else:
            self._state = self._insert_jit(
                self._state, np.int32(slot), cache["k"], cache["v"], tok[0],
                np.int32(req.prompt_len), np.int32(req.max_new_tokens - 1),
                keys[1], np.float32(s.temperature), np.int32(s.top_k),
                np.float32(s.top_p), np.int32(-1 if eos is None else eos))
        events.append(TokenEvent(req.request_id, t, 0, False, None, now))

    # ----------------------------------------------- chunked prefill driver
    def _advance_prefill(self, events):
        """Run ONE prefill chunk of the oldest pending job (the whole
        remaining suffix when chunking is off — preemption-resume replays).
        Each chunk is a suffix-prefill call: the q block is written at the
        job's cursor against its donated partial cache, bucketed so every
        full chunk shares one compiled program."""
        job = self._prefill_jobs[0]
        remaining = len(job.ids) - job.pos
        n = min(self.chunk_size, remaining) \
            if self.chunked else remaining
        # ceiling shrinks by the already-prefilled prefix (same overrun
        # guard as the shared-prefix suffix path: a bucket past max_len
        # would make XLA clamp the q-block write start)
        padded = self.engine._bucket_prompt_len(n, self.max_len - job.pos)
        req = job.req
        req.chunks += 1
        req.padding_tokens += padded - n
        if job.resume:
            # every replayed position is device work a preemption burned:
            # it was prefilled (prompt) or decoded (generated) once already
            req.replay_tokens += n
        self.metrics.record_prefill_work(padded, n,
                                         replay=n if job.resume else 0)
        with self.tracer.span("prefill_chunk", cat="serving",
                              request_id=req.request_id,
                              trace_id=req.trace_id, n=n,
                              padded_len=padded, start=job.pos,
                              resume=job.resume):
            ids = np.zeros((1, padded), np.int32)
            ids[0, :n] = job.ids[job.pos:job.pos + n]
            logits, job.cache = self._suffix_program(padded)(
                self.engine.params, jnp.asarray(ids), job.cache,
                np.int32(job.pos), np.int32(n))
            self.clock.advance(
                padded * self.cfg.virtual_prefill_cost_per_token)
        job.pos += n
        self._decode_steps_since_chunk = 0
        if job.done:
            self._prefill_jobs.popleft()
            self._complete_job(job, logits, events)

    def _complete_job(self, job, logits, events):
        req = job.req
        if not job.resume:
            self._after_prefill(req, job.cache, job.shared_len,
                                job.shared_blocks, logits, events,
                                slot=job.slot)
            return
        # resume: splice back at the saved cursor with the rng captured at
        # preemption — no first token is sampled (the last streamed token is
        # re-fed at the cursor), so the stream continues bitwise-identically
        slot, s, eos = job.slot, req.sampling, req.eos_token_id
        remaining = req.max_new_tokens - len(req.tokens)
        req.state = RequestState.RUNNING
        self._slots[slot] = req
        req.slot = slot
        rng = jnp.asarray(req.resume_rng)
        # committed replicated scalar: the fresh path feeds tok[0] straight
        # out of _sample_first_jit (committed to the mesh via its pinned
        # out_shardings), and an uncommitted host scalar here would open a
        # SECOND jit-cache entry for the same aval — breaking the
        # insert-compiles-once pin
        tok = jax.device_put(jnp.asarray(req.tokens[-1], jnp.int32),
                             self._rep_sharding)
        if self.paged:
            self._insert_paged(req, slot, job.cache, job.shared_len,
                               job.shared_blocks, tok,
                               rng, s, eos, remaining)
        else:
            self._state = self._insert_jit(
                self._state, np.int32(slot), job.cache["k"], job.cache["v"],
                tok, np.int32(self._prefill_len(req)),
                np.int32(remaining), rng, np.float32(s.temperature),
                np.int32(s.top_k), np.float32(s.top_p),
                np.int32(-1 if eos is None else eos))
        self.tracer.instant("request/resumed", cat="serving",
                            ts=self.clock.now(), request_id=req.request_id,
                            trace_id=req.trace_id,
                            n_tokens=len(req.tokens),
                            preemptions=req.preemptions,
                            # positions this resume re-prefilled (the wide
                            # event's replay attribution per round trip)
                            replay_tokens=len(job.ids) - job.shared_len)

    # ------------------------------------------------- on-demand growth
    def _grow_or_preempt(self):
        """Reserve-as-you-decode: before the decode step, any active slot
        whose write cursor reached the end of its bound blocks grows by one
        block; when the pool can't provide one, the NEWEST-admitted running
        request is preempted back to the queue head (its blocks free, its
        stream resumes bitwise-identically later) instead of OOM/shed."""
        mgr = self.pool_mgr
        for slot in sorted(list(self._slots)):
            req = self._slots.get(slot)
            if req is None:
                continue  # preempted earlier in this same pass
            pos = req.prompt_len + len(req.tokens) - 1  # this step's write
            j = pos // mgr.block_size
            if j < mgr.slot_block_count(slot):
                continue
            preempted_self = False
            while not mgr.can_allocate(1):
                # victim order: batch class before interactive (QoS), then
                # newest admission first — a legacy all-interactive pool
                # reduces to the original newest-admission rule exactly
                victim = max(self._slots, key=lambda s_: (
                    self._slots[s_].tenant_class == CLASS_BATCH,
                    self._slots[s_].admit_seq))
                self._preempt(victim)
                if victim == slot:
                    preempted_self = True
                    break
            if preempted_self:
                continue
            bid = mgr.grow_slot(slot, live_tokens=pos + 1)
            req.kv_blocks_peak = max(req.kv_blocks_peak, j + 1)
            self._state = self._grow_jit(self._state, np.int32(slot),
                                         np.int32(j), np.int32(bid))

    def _preempt(self, slot):
        """Preempt-to-queue: capture the slot's rng (the resume replay needs
        the exact stream), release its blocks and table row, and push the
        request back to the QUEUE HEAD (it outranks everything queued behind
        it — FCFS by original admission)."""
        req = self._slots.pop(slot)
        req.resume_rng = np.asarray(self._state["rng"])[slot].copy()
        req.preemptions += 1
        self.pool_mgr.preempted_requests += 1
        self.metrics.record_preempt()
        self._state = self._release_jit(self._state, np.int32(slot))
        self.pool_mgr.free_slot(slot)
        self._free_slots.append(slot)
        if self._drafter is not None:
            self._drafter.release(slot)
        req.slot = None
        self.queue.push_front(req)
        self.tracer.instant("request/preempted", cat="serving",
                            ts=self.clock.now(), request_id=req.request_id,
                            trace_id=req.trace_id,
                            n_tokens=len(req.tokens))

    def _insert_paged(self, req, slot, cache, shared_len, shared_blocks,
                      tok, chain_key, s, eos, remaining):
        """Bind a paged slot: allocate the request's footprint in blocks,
        copy the freshly-prefilled PRIVATE blocks from the dense cache
        (shared prefix blocks are refcounted, never copied — copy-on-write),
        set the slot's table row + scalars, and content-address the full
        prompt blocks for future prefix hits. Under on-demand growth the
        footprint is only the PREFILLED positions; decode blocks arrive via
        ``_grow_or_preempt`` as the cursor advances."""
        mgr = self.pool_mgr
        prefill_len = self._prefill_len(req)
        needed = mgr.blocks_for_prefill(self._growth_admission_len(req)) \
            if self.growth \
            else mgr.blocks_for(req.prompt_len, req.max_new_tokens)
        # the scheduler's can_admit reserved this; alloc may still evict
        self._unreserve(req)
        private = mgr.alloc(needed - len(shared_blocks))
        blocks = list(shared_blocks) + private
        ids = np.full((mgr.blocks_per_slot,), GARBAGE_BLOCK, np.int32)
        srcs = np.zeros((mgr.blocks_per_slot,), np.int32)
        for i, bid in enumerate(private):
            ids[i] = bid
            srcs[i] = (len(shared_blocks) + i) * mgr.block_size
        self._state = self._insert_block_jit(
            self._state, cache["k"], cache["v"], jnp.asarray(ids),
            jnp.asarray(srcs))
        row = np.full((mgr.blocks_per_slot,), GARBAGE_BLOCK, np.int32)
        row[:len(blocks)] = blocks
        self._state = self._insert_jit(
            self._state, np.int32(slot), jnp.asarray(row), tok,
            np.int32(prefill_len), np.int32(remaining),
            chain_key, np.float32(s.temperature), np.int32(s.top_k),
            np.float32(s.top_p), np.int32(-1 if eos is None else eos))
        mgr.bind_slot(slot, blocks,
                      self._growth_admission_len(req) if self.growth
                      else req.prompt_len + req.max_new_tokens - 1)
        req.kv_blocks_peak = max(req.kv_blocks_peak, len(blocks))
        mgr.register_prefix(req.prompt, blocks)

    # ------------------------------------------------- live KV migration
    def _pool_leaf_names(self):
        return ("k", "v", "k_scale", "v_scale") \
            if self.cfg.kv_pool.kv_dtype == "int8" else ("k", "v")

    def _pool_geometry(self):
        """The splice-compatibility fingerprint a ``RequestSnapshot``
        carries: a snapshot only splices into a pool whose physical block
        layout is identical — anything else falls back to replay-resume."""
        cfg = self.engine.module.config
        return (cfg.n_layers, self.pool_mgr.block_size, cfg.kv_heads,
                cfg.head_dim,
                str(self.cfg.kv_pool.kv_dtype or np.dtype(self.engine.dtype)))

    def capture_snapshot(self, req):
        """Serialize a RUNNING request's device state into a portable
        :class:`RequestSnapshot` (between scheduler steps): the physical
        pool blocks holding positions ``[0, pos)`` as RAW pool-dtype bytes,
        the cursor, the per-slot rng chain key, the committed tokens, the
        sampling knobs, and the prompt's SHA-256 prefix chain keys. Host
        gathers only — no new compiled program, no device mutation — so a
        capture can run on any step boundary without perturbing the
        stay-put stream."""
        if not self.paged or req.slot is None \
                or self._slots.get(req.slot) is not req:
            return None
        mgr = self.pool_mgr
        slot = req.slot
        pos = req.prompt_len + len(req.tokens) - 1  # KV coverage [0, pos)
        cover = -(-pos // mgr.block_size)           # ceil: blocks holding it
        nb = min(mgr.slot_block_count(slot), cover)
        if nb <= 0:
            return None
        row = np.asarray([mgr.slot_block(slot, j) for j in range(nb)],
                         np.int32)
        raw = {name: np.asarray(self._state[name][:, row])
               for name in self._pool_leaf_names()}
        s = req.sampling
        snap = RequestSnapshot(
            request_id=req.request_id, prompt=req.prompt, tokens=req.tokens,
            pos=pos, rng=np.asarray(self._state["rng"])[slot].copy(),
            blocks=raw, block_size=mgr.block_size,
            chain_keys=prefix_chain_keys(req.prompt, mgr.block_size),
            temperature=s.temperature, top_k=s.top_k, top_p=s.top_p,
            seed=s.seed, max_new_tokens=req.max_new_tokens,
            eos_token_id=req.eos_token_id, geometry=self._pool_geometry())
        req.migration = snap
        self.metrics.record_snapshot()
        return snap

    def _maybe_snapshot(self):
        """Periodic snapshot cadence (``serving.migration
        .snapshot_interval_tokens``): re-capture a running request once it
        has committed that many tokens past its last snapshot — the bound
        a replica-kill recovery replays from."""
        interval = self.cfg.migration.snapshot_interval_tokens
        for slot in sorted(self._slots):
            req = self._slots[slot]
            have = len(req.migration.tokens) \
                if req.migration is not None else 0
            if len(req.tokens) - have >= interval:
                self.capture_snapshot(req)

    def chain_key_for_resume(self, req):
        """The per-slot rng chain key a replayed request must re-enter with
        when NO snapshot exists (replica killed before the first cadence
        capture): re-derive the insert-time chain key deterministically
        from the request's seed and advance it by the committed decode
        steps, exactly as the compiled decode would have."""
        return advance_rng(np.asarray(self._request_key(req)[1]),
                           len(req.tokens) - 1)

    def _inject_raw(self, snap, blocks, n_shared, n_inject):
        """The device half of a splice: copy snapshot source blocks
        ``[n_shared, n_shared + n_inject)`` into the pool blocks of the
        same index. Non-int8 pools ride the EXISTING compiled
        insert_blocks program (their dense view is the raw bytes, and the
        compiled-once pin holds — the dense source is device_put with the
        same pinned cache sharding prefill outputs carry); int8 pools run
        the dedicated raw program so payload AND scales move verbatim."""
        mgr = self.pool_mgr
        bs = mgr.block_size
        ids = np.full((mgr.blocks_per_slot,), GARBAGE_BLOCK, np.int32)
        if self.cfg.kv_pool.kv_dtype == "int8":
            raw = {}
            for name, a in snap.blocks.items():
                pad = np.zeros((a.shape[0], mgr.blocks_per_slot)
                               + a.shape[2:], a.dtype)
                pad[:, :a.shape[1]] = a
                raw[name] = jax.device_put(pad, self._cache_sharding)
            for i in range(n_shared, n_shared + n_inject):
                ids[i] = blocks[i]
            self._state = self._migrate_in_jit(self._state, raw,
                                               jnp.asarray(ids))
            return
        dense = {}
        for name in ("k", "v"):
            a = snap.blocks[name]
            d = np.zeros((a.shape[0], 1, self.max_len) + a.shape[3:],
                         np.dtype(self.engine.dtype))
            d[:, 0, :a.shape[1] * bs] = \
                a.reshape((a.shape[0], -1) + a.shape[3:])
            dense[name] = jax.device_put(d, self._cache_sharding)
        srcs = np.zeros((mgr.blocks_per_slot,), np.int32)
        for i in range(n_shared, n_shared + n_inject):
            ids[i] = blocks[i]
            srcs[i] = i * bs
        self._state = self._insert_block_jit(
            self._state, dense["k"], dense["v"], jnp.asarray(ids),
            jnp.asarray(srcs))

    def _splice_snapshot(self, req, snap, ids_full, shared_len,
                         shared_blocks):
        """Splice a migrated request's snapshot into this replica instead
        of replaying it. FRESH snapshot (captured at the current commit
        point — drain-by-migration): every computed position lands
        verbatim, including the partial tail block (its garbage rows past
        the cursor are causally masked, exactly as on the stay-put
        replica), and the request re-enters the decode pool directly —
        zero recompute. STALE snapshot (periodic cadence, after a kill):
        the FULL blocks splice and only the tail since the capture replays
        through the standard resume-prefill machinery (counted as replay
        tokens). Prefix-cache hits on the target always win first: blocks
        the target already shares are taken by reference, never copied.
        Returns False (no side effects) when the prefix hit already covers
        the snapshot — the caller falls through to the normal path."""
        mgr = self.pool_mgr
        bs = mgr.block_size
        prefill_len = self._prefill_len(req)
        n_shared = len(shared_blocks)
        fresh = snap.pos >= prefill_len
        cover = min(-(-snap.pos // bs) if fresh else snap.full_blocks,
                    mgr.blocks_per_slot)
        if cover <= n_shared:
            return False
        delta = len(req.tokens) - len(snap.tokens)
        self.clock.advance(
            (cover - n_shared) * self.cfg.migration.virtual_cost_per_block)
        if fresh:
            slot = self._free_slots.pop()
            needed = mgr.blocks_for_prefill(self._growth_admission_len(req)) \
                if self.growth \
                else mgr.blocks_for(req.prompt_len, req.max_new_tokens)
            self._unreserve(req)
            private = mgr.alloc(needed - n_shared)
            blocks = list(shared_blocks) + private
            n_inject = min(cover, len(blocks)) - n_shared
            self._inject_raw(snap, blocks, n_shared, n_inject)
            row = np.full((mgr.blocks_per_slot,), GARBAGE_BLOCK, np.int32)
            row[:len(blocks)] = blocks
            # committed replicated scalar, same reason as _complete_job:
            # an uncommitted host scalar would open a second jit-cache
            # entry and break the insert-compiles-once pin
            tok = jax.device_put(jnp.asarray(req.tokens[-1], jnp.int32),
                                 self._rep_sharding)
            rng = jnp.asarray(advance_rng(snap.rng, delta))
            s, eos = req.sampling, req.eos_token_id
            self._state = self._insert_jit(
                self._state, np.int32(slot), jnp.asarray(row), tok,
                np.int32(prefill_len),
                np.int32(req.max_new_tokens - len(req.tokens)), rng,
                np.float32(s.temperature), np.int32(s.top_k),
                np.float32(s.top_p), np.int32(-1 if eos is None else eos))
            mgr.bind_slot(slot, blocks,
                          self._growth_admission_len(req) if self.growth
                          else req.prompt_len + req.max_new_tokens - 1)
            req.kv_blocks_peak = max(req.kv_blocks_peak, len(blocks))
            mgr.register_prefix(req.prompt, blocks)
            req.state = RequestState.RUNNING
            self._slots[slot] = req
            req.slot = slot
            if req.admit_seq < 0:
                req.admit_seq = self._admit_seq
                self._admit_seq += 1
            saved = min(cover * bs, snap.pos) - n_shared * bs
            replay = 0
        else:
            n_inject = cover - n_shared
            # the admission reservation covers these blocks: consume our
            # own share BEFORE alloc so the target's pending count stays
            # honest (and never eats another request's reservation)
            mgr.consume_reservation(min(n_inject, req.reserved_blocks))
            req.reserved_blocks = max(req.reserved_blocks - n_inject, 0)
            blocks = list(shared_blocks) + mgr.alloc(n_inject)
            self._inject_raw(snap, blocks, n_shared, n_inject)
            slot = self._free_slots.pop()
            row = np.full((mgr.blocks_per_slot,), GARBAGE_BLOCK, np.int32)
            row[:len(blocks)] = blocks
            cache = self._seed_cache_jit(self._state, jnp.asarray(row))
            # teacher-forced tail: the tokens committed after the capture
            # replay as prefill, and the rng re-joins the original chain
            req.resume_rng = advance_rng(snap.rng, delta)
            self._prefill_jobs.append(_PrefillJob(
                req=req, slot=slot, cache=cache,
                ids=np.asarray(ids_full, np.int32), pos=cover * bs,
                shared_len=cover * bs, shared_blocks=blocks, resume=True))
            saved = n_inject * bs
            replay = len(ids_full) - cover * bs
        if shared_len:
            # the dedupe win: positions the target's prefix cache already
            # held, so the splice never re-sent their blocks (a resume
            # replay is not credited, but a migrated snapshot arriving over
            # the wire is genuinely avoided transfer + prefill work)
            req.prefix_saved_tokens += shared_len
            self.metrics.prefix_saved_tokens += shared_len
        req.migrations += 1
        self.metrics.record_migration_in(saved)
        # the handoff instant pair's IN side: a first-token prefill->decode
        # handoff splice is telemetered distinctly from a recovery splice
        # (same machinery, different latency semantics — wide events charge
        # the out->in gap to "handoff", not "migrated")
        name = "request/migrated"
        if req.handoff_pending:
            name = "request/handoff_in"
            req.handoff_pending = False
            req.handoffs += 1
        self.tracer.instant(name, cat="serving",
                            ts=self.clock.now(), request_id=req.request_id,
                            trace_id=req.trace_id, n_tokens=len(req.tokens),
                            spliced_blocks=n_inject, shared_len=shared_len,
                            saved_tokens=saved, replay_tokens=replay,
                            fresh=fresh)
        return True

    def evacuate_request(self, req, instant="request/migrated_out"):
        """Live-move ONE running stream off this replica: capture a FRESH
        snapshot while the slot binding is live (the ownership guard in
        ``capture_snapshot`` rejects an unbound request), release the
        slot's device state, and hand the request back QUEUED for
        re-dispatch on a peer. This is the unit the first-token handoff
        (``instant="request/handoff_out"``) and the rebalancer move;
        ``evacuate()`` is this over every slot. Returns False when the
        request is not a slot-bound stream here (nothing to move)."""
        slot = req.slot
        if slot is None or self._slots.get(slot) is not req:
            return False
        if self.paged and self.cfg.migration.enabled:
            self.capture_snapshot(req)
        self._slots.pop(slot)
        # keep the plain resume path viable too (snapshot may not
        # splice on the target): the rng at this commit point
        req.resume_rng = np.asarray(self._state["rng"])[slot].copy()
        self._state = self._release_jit(self._state, np.int32(slot))
        if self.paged:
            self.pool_mgr.free_slot(slot)
        if self._drafter is not None:
            self._drafter.release(slot)
        self._free_slots.append(slot)
        req.slot = None
        req.state = RequestState.QUEUED
        self.metrics.record_migration_out()
        self.tracer.instant(instant, cat="serving",
                            ts=self.clock.now(),
                            request_id=req.request_id,
                            trace_id=req.trace_id,
                            n_tokens=len(req.tokens),
                            snapshot=req.migration is not None)
        return True

    def evacuate(self):
        """Drain-by-migration: capture a FRESH snapshot of every running
        request, release its device state, and hand every unfinished
        request back (original admission order) for re-dispatch on a peer
        replica — a drained replica restarts with ZERO lost and (when the
        snapshot splices) zero recomputed tokens. Pending prefill jobs and
        the queue ride along as-is: their work is not on this device yet
        beyond the shared prefix."""
        out = []
        for slot in sorted(self._slots,
                           key=lambda s_: self._slots[s_].admit_seq):
            req = self._slots[slot]
            self.evacuate_request(req)
            out.append(req)
        for job in list(self._prefill_jobs):
            req = job.req
            if self.paged:
                self.pool_mgr.release_blocks(job.shared_blocks)
            self._unreserve(req)
            self._free_slots.append(job.slot)
            req.slot = None
            req.state = RequestState.QUEUED
            out.append(req)
        self._prefill_jobs.clear()
        while self.queue.depth:
            out.append(self.queue.pop())
        return out

    def abandon_inflight(self):
        """A killed replica's post-mortem: collect every unfinished request
        WITHOUT touching the device (the replica is gone — no capture, no
        release; recovery runs from whatever snapshot the periodic cadence
        already took, or replays the prompt + committed tokens). Host
        bookkeeping only: reservations are zeroed ON THE REQUEST — the
        pool they were pending against died with the replica, and carrying
        them to a survivor would eat its reservations."""
        out = []
        for slot in sorted(self._slots,
                           key=lambda s_: self._slots[s_].admit_seq):
            req = self._slots.pop(slot)
            req.slot = None
            req.state = RequestState.QUEUED
            req.reserved_blocks = 0
            out.append(req)
        for job in list(self._prefill_jobs):
            req = job.req
            req.slot = None
            req.state = RequestState.QUEUED
            req.reserved_blocks = 0
            out.append(req)
        self._prefill_jobs.clear()
        while self.queue.depth:
            req = self.queue.pop()
            req.reserved_blocks = 0
            out.append(req)
        return out

    # ------------------------------------------------- speculative decoding
    def set_speculation(self, enabled):
        """Toggle speculation at runtime (drafting is skipped when off; the
        compiled verify program stays warm). Seeded sampled streams are
        unaffected either way — the rng splits once per dispatched step in
        both the decode and verify programs (tier-1 pins it)."""
        self._spec_on = bool(enabled) and self.spec

    def _collect_drafts(self):
        """Ask the drafter for up to k candidates per eligible slot.

        Eligibility: active, GREEDY (sampled slots never speculate — greedy
        acceptance is an argmax identity, and a sampled slot's rng must
        advance exactly once per dispatched step), and >= 2 tokens still
        owed (a 1-token tail gains nothing from drafting). Draft length is
        capped at tokens-owed - 1 (so every written candidate row stays
        inside the request's block footprint) and, under on-demand growth,
        by the coverage the pool can provide RIGHT NOW: a k-token verify
        may cross a block boundary, and the grow must land before the
        dispatch — exactly the admission-coverage bug class PR 13's
        instrument caught, handled here by growing (never preempting) for
        speculation and truncating the drafts when the pool is tight."""
        wanted = {}
        for slot, req in self._slots.items():
            if req.sampling.temperature > 0:
                continue
            owed = req.max_new_tokens - len(req.tokens)
            cap = min(self.spec_k, owed - 1)
            if cap < 1:
                continue
            wanted[slot] = (np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)]), cap)
        if not wanted:
            return None
        proposals = self._drafter.propose(wanted)
        out = {}
        proposed = 0
        for slot, toks in proposals.items():
            toks = np.asarray(toks, np.int32).reshape(-1)[:wanted[slot][1]]
            proposed += len(toks)
            req = self._slots[slot]
            if self.growth and len(toks):
                toks = self._grow_for_verify(slot, req, toks)
            if len(toks):
                out[slot] = toks
                req.drafted_tokens += len(toks)
                self.metrics.record_draft(len(toks))
        if proposed and self._drafter.name == "model":
            self.clock.advance(
                proposed * self.cfg.speculative.virtual_draft_cost_per_token)
        return out or None

    def _grow_for_verify(self, slot, req, toks):
        """Under on-demand growth, candidate rows at positions
        [cursor, cursor + len(toks)] must be block-covered BEFORE the
        verify dispatches (padded rows redirect to the garbage block, but
        rows that could be ACCEPTED must land in real blocks). Grows one
        block at a time; when the pool cannot provide one, the drafts are
        truncated to the existing coverage — speculation is opportunistic
        and never preempts another request to make room for itself."""
        mgr = self.pool_mgr
        pos = req.prompt_len + len(req.tokens) - 1
        while (pos + len(toks)) // mgr.block_size \
                >= mgr.slot_block_count(slot):
            if not mgr.can_allocate(1):
                cover = mgr.slot_block_count(slot) * mgr.block_size
                return toks[:max(cover - 1 - pos, 0)]
            j = mgr.slot_block_count(slot)
            bid = mgr.grow_slot(slot, live_tokens=pos + 1)
            req.kv_blocks_peak = max(req.kv_blocks_peak, j + 1)
            self._state = self._grow_jit(self._state, np.int32(slot),
                                         np.int32(j), np.int32(bid))
        return toks

    def _verify_once(self, events, drafts):
        """One verify dispatch over the whole pool: every active slot
        advances >= 1 token (row 0 is its decode), speculating slots
        advance by the accepted prefix + 1. Costs ONE decode step in
        virtual time — that is the entire latency play, and why the worst
        inter-token gap bound from chunked prefill is unchanged."""
        kk = self.spec_k
        dmat = np.zeros((self.n_slots, kk), np.int32)
        dlen = np.zeros((self.n_slots,), np.int32)
        for slot, toks in drafts.items():
            dmat[slot, :len(toks)] = toks
            dlen[slot] = len(toks)
        with self.tracer.span("decode_step", cat="serving",
                              active=len(self._slots), verify=True,
                              drafted=int(dlen.sum())):
            ((toks, n_emit, accepted, done_now, nonfinite),
             self._state) = self._verify_jit(
                self.engine.params, self._state, jnp.asarray(dmat),
                jnp.asarray(dlen))
            self.clock.advance(self.cfg.virtual_decode_step_cost)
        toks = np.asarray(toks)
        n_emit = np.asarray(n_emit)
        accepted = np.asarray(accepted)
        done_now = np.asarray(done_now)
        nonfinite = np.asarray(nonfinite)
        now = self.clock.now()
        self.metrics.record_health_step(
            sum(1 for s in self._slots if nonfinite[s] > 0))
        self.metrics.record_verify_step()
        self.metrics.record_decode_dispatch()
        for slot in sorted(self._slots):
            req = self._slots[slot]
            pos0 = req.prompt_len + len(req.tokens) - 1  # this step's cursor
            n, acc, d = int(n_emit[slot]), int(accepted[slot]), \
                int(dlen[slot])
            if d:
                # booked BEFORE any shed below: the drafted == accepted +
                # rolled_back invariant must balance on every exit path
                req.accepted_tokens += acc
                req.rolled_back_tokens += d - acc
                self.metrics.record_accept(acc, d - acc)
            if self._health_shed and nonfinite[slot] > 0:
                self._shed_unhealthy(req, events, now, int(nonfinite[slot]))
                continue
            reason = None
            for j in range(n):
                t = int(toks[slot, j])
                req.tokens.append(t)
                self.metrics.record_tokens(1, req)
                self.metrics.record_decode_tokens(1)
                if j == n - 1 and bool(done_now[slot]):
                    reason = FINISH_EOS if (req.eos_token_id is not None
                                            and t == req.eos_token_id) \
                        else FINISH_LENGTH
                elif t in req.stop_token_ids:
                    # host-side stop policy truncates the emitted run; the
                    # device state is ahead but the slot is freed anyway
                    reason = FINISH_STOP
                events.append(TokenEvent(req.request_id, t,
                                         len(req.tokens) - 1,
                                         reason is not None, reason, now))
                if reason is not None:
                    break
            if reason is not None:
                self._finish(req, reason, now,
                             deactivate=(reason == FINISH_STOP))
                continue
            if d >= n:
                # candidate rows [pos0 + n, pos0 + d] were written but the
                # cursor rolled back short of them — reclaim at block
                # granularity
                self._rollback_stale(slot, new_cursor=pos0 + n,
                                     written_end=pos0 + d)

    def _rollback_stale(self, slot, new_cursor, written_end):
        """Rejected drafts rolled back: the in-graph verify already left
        the cursor at the accepted end, so the rejected rows sit PAST it —
        causally masked and overwritten before they could ever become
        visible (the same guarantee freed-slot garbage rides). At block
        granularity more is reclaimable: a block lying entirely past the
        cursor holds ONLY stale rows, so under on-demand growth it is
        released back to the pool (its scrub rides the normal last-ref
        drop) and under whole-footprint reservation it is scrubbed in
        place when the hygiene scrub is armed — both counted in
        ``scrubbed_blocks``/``rolled_back_blocks``."""
        mgr = self.pool_mgr
        first_stale = -(-new_cursor // mgr.block_size)   # ceil
        if self.growth:
            for j in range(mgr.slot_block_count(slot) - 1, first_stale - 1,
                           -1):
                # table entry retreats to the garbage block BEFORE the
                # allocator can hand the block to anyone else
                self._state = self._grow_jit(self._state, np.int32(slot),
                                             np.int32(j),
                                             np.int32(GARBAGE_BLOCK))
                mgr.shrink_slot(slot, live_tokens=new_cursor)
        elif self.cfg.scrub_freed_slots:
            last = min(written_end // mgr.block_size,
                       mgr.slot_block_count(slot) - 1)
            for j in range(first_stale, last + 1):
                self._scrub_block(mgr.slot_block(slot, j))
                mgr.scrubbed_blocks += 1

    def _decode_once(self, events):
        with self.tracer.span("decode_step", cat="serving",
                              active=len(self._slots)):
            ((toks, done_now, nonfinite),
             self._state) = self._decode_jit(self.engine.params, self._state)
            self.clock.advance(self.cfg.virtual_decode_step_cost)
        self.metrics.record_decode_dispatch()
        toks = np.asarray(toks)
        done_now = np.asarray(done_now)
        nonfinite = np.asarray(nonfinite)
        now = self.clock.now()
        self.metrics.record_health_step(
            sum(1 for s in self._slots if nonfinite[s] > 0))
        for slot in sorted(self._slots):
            req = self._slots[slot]
            t = int(toks[slot])
            if self._health_shed and nonfinite[slot] > 0:
                self._shed_unhealthy(req, events, now, int(nonfinite[slot]))
                continue
            req.tokens.append(t)
            self.metrics.record_tokens(1, req)
            self.metrics.record_decode_tokens(1)
            if bool(done_now[slot]):
                reason = FINISH_EOS if (req.eos_token_id is not None
                                        and t == req.eos_token_id) \
                    else FINISH_LENGTH
            elif t in req.stop_token_ids:
                # stop sequences are host-side policy (a set, not the single
                # device-tracked eos id): finish here and deactivate the slot
                reason = FINISH_STOP
            else:
                events.append(TokenEvent(req.request_id, t,
                                         len(req.tokens) - 1, False, None,
                                         now))
                continue
            self._finish(req, reason, now, deactivate=(reason == FINISH_STOP))
            events.append(TokenEvent(req.request_id, t, len(req.tokens) - 1,
                                     True, reason, now))

    def _shed_unhealthy(self, req, events, now, n_bad):
        """The unhealthy_slot hook, shared by the decode and verify paths:
        this slot's logits went non-finite — its sampled token is poison,
        its KV rows are suspect. Shed the request with a reason (the
        admission-control discipline: fail loudly, never stream garbage)
        and free + deactivate the slot."""
        self.metrics.record_shed("unhealthy_slot")
        self.metrics.record_unhealthy()
        self.tracer.instant("request/unhealthy", cat="serving", ts=now,
                            request_id=req.request_id,
                            trace_id=req.trace_id, nonfinite_logits=n_bad)
        self._finish(req, FINISH_UNHEALTHY, now, deactivate=True)
        events.append(TokenEvent(req.request_id, -1, len(req.tokens),
                                 True, FINISH_UNHEALTHY, now))

    def _finish(self, req, reason, now, deactivate=False):
        """``deactivate``: the device doesn't know this slot finished (host-
        side stop policy) — clear its active flag so decode stops advancing
        it. EOS/length finishes already cleared it inside the decode step."""
        req.state = RequestState.FINISHED
        req.finish_reason = reason
        req.finish_time = now
        if req.slot is not None:
            del self._slots[req.slot]
            self._free_slots.append(req.slot)
            if self._drafter is not None:
                self._drafter.release(req.slot)
            if self.paged:
                # ALWAYS release under paging: the table row must retreat
                # to the garbage block before the allocator reuses the
                # blocks (the dense pool's rows are private, so it only
                # releases for host-side stops / the hygiene scrub)
                self._state = self._release_jit(self._state,
                                                np.int32(req.slot))
                self.pool_mgr.free_slot(req.slot)
            elif deactivate or self.cfg.scrub_freed_slots:
                self._state = self._release_jit(self._state,
                                                np.int32(req.slot))
            req.slot = None
        self.metrics.record_finish(req)
        start = req.start_time
        # the per-request goodput/lifecycle rollup rides the finish instant
        # verbatim, so the fleet merger's wide event needs no cross-stream
        # reconstruction of engine-side counters. admit_wait splits the
        # queue wait: arrival -> scheduler admission (waiting for a slot /
        # KV blocks) vs admission -> prefill dispatch.
        self.tracer.instant("request/finish", cat="serving", ts=now,
                            request_id=req.request_id,
                            trace_id=req.trace_id, reason=reason,
                            n_tokens=len(req.tokens),
                            prompt_len=req.prompt_len,
                            # multi-tenant QoS: the wide event carries the
                            # tenant so fleet_report can grade per tenant
                            tenant_id=req.tenant_id,
                            tenant_class=req.tenant_class,
                            priority_evictions=req.priority_evictions,
                            queue_wait=req.queue_wait,
                            admit_wait=None
                            if req.admit_time is None or start is None
                            else req.admit_time - start,
                            chunks=req.chunks,
                            preemptions=req.preemptions,
                            replay_tokens=req.replay_tokens,
                            padding_tokens=req.padding_tokens,
                            prefix_saved_tokens=req.prefix_saved_tokens,
                            kv_blocks_peak=req.kv_blocks_peak,
                            # speculative accounting: the wide event's
                            # drafted/accepted/rolled_back counts reconcile
                            # with the fleet counters (tier-1-pinned)
                            drafted_tokens=req.drafted_tokens,
                            accepted_tokens=req.accepted_tokens,
                            rolled_back_tokens=req.rolled_back_tokens,
                            # fleet recovery accounting: completed replica
                            # moves and the bounded failover/retry budget
                            # spent (router-owned, but the Request object
                            # is the same across replicas)
                            migrations=req.migrations,
                            failovers=req.failovers,
                            retries=req.retries,
                            # disaggregated fleet: first-token handoffs and
                            # voluntary rebalance moves this stream rode
                            handoffs=req.handoffs,
                            rebalances=req.rebalances)

    # ------------------------------------------------------------- frontends
    def serve(self, requests=None, yield_rejections=True):
        """Streaming frontend: feed ``requests`` (each optionally carrying an
        ``arrival_time``) through the continuous-batching loop, yielding
        ``TokenEvent``s as they are produced. Runs until every accepted
        request finishes; shed requests surface as a single done event with
        ``finish_reason="rejected:<reason>"`` (and ``token == -1``)."""
        pending = sorted((as_request(r) for r in (requests or [])),
                         key=lambda r: r.arrival_time or 0.0)
        t0 = self.clock.now()
        for r in pending:
            # arrival offsets -> absolute clock times (TTFT counts queueing)
            if not r.arrival_resolved:
                r.arrival_time = t0 + (r.arrival_time or 0.0)
                r.arrival_resolved = True
            elif r.arrival_time is None:
                r.arrival_time = t0
        try:
            while pending or self.queue.depth or self._slots \
                    or self._prefill_jobs:
                now = self.clock.now()
                while pending and pending[0].arrival_time <= now:
                    req = self.submit(pending.pop(0))
                    if req.state is RequestState.REJECTED and yield_rejections:
                        yield TokenEvent(req.request_id, -1, -1, True,
                                         f"rejected:{req.reject_reason}", now)
                if not self._slots and not self.queue.depth \
                        and not self._prefill_jobs:
                    if not pending:
                        break
                    # idle until the next arrival
                    self.clock.sleep(max(pending[0].arrival_time - now, 1e-4))
                    continue
                for ev in self.step():
                    yield ev
        finally:
            # a consumer that breaks mid-stream (GeneratorExit) or a step()
            # exception must still land the lifecycle events on disk — this
            # is the only flush on the streaming path before destroy().
            # The terminal metrics emit closes the rate-limited monitor
            # cadence: short runs lose no tail interval (the Router does the
            # same fleet-wide).
            self.tracer.flush()
            self.metrics.emit_events()

    def run(self, requests):
        """Non-streaming convenience: serve ``requests`` to completion and
        return ``(finished, rejected, metrics_snapshot)``."""
        reqs = [as_request(r) for r in (requests or [])]
        for _ in self.serve(reqs, yield_rejections=False):
            pass
        finished = [r for r in reqs if r.state is RequestState.FINISHED]
        rejected = [r for r in reqs if r.state is RequestState.REJECTED]
        return finished, rejected, self.metrics.snapshot()

    def destroy(self):
        """Drop the slot pool and compiled programs (cf. InferenceEngine
        .destroy): the jitted closures capture self, which would otherwise
        pin the KV pool in HBM."""
        self._state = None
        self._decode_jit = None
        self._insert_jit = None
        self._release_jit = None
        self._sample_first_jit = None
        self._insert_block_jit = None
        self._seed_cache_jit = None
        self._scrub_jit = None
        self._fresh_cache_jit = None
        self._grow_jit = None
        self._verify_jit = None
        self._migrate_in_jit = None
        if self._drafter is not None and hasattr(self._drafter, "destroy"):
            self._drafter.destroy()
        self._drafter = None
        self._prefill_programs = OrderedDict()
        self._suffix_programs = OrderedDict()
        self._prefill_jobs = collections.deque()
        self._slots = {}
        self._free_slots = list(range(self.n_slots - 1, -1, -1))
        self.tracer.flush()
        import gc

        gc.collect()
