"""Continuous-batching serving subsystem.

Slot-pool scheduler (one jitted decode program, requests join/leave
mid-flight), bounded-queue admission control, streaming token events, and
Serving/* metrics — the request-level layer that turns the single-call
``InferenceEngine`` roofline into sustained multi-tenant throughput.
"""

from .clock import VirtualClock, WallClock
from .control import (DEGRADED_LADDER, Autoscaler, BurnSensor,
                      DegradedModeController)
from .engine import ServingEngine
from .kv_pool import GARBAGE_BLOCK, KVPoolManager, prefix_chain_keys
from .metrics import ServingMetrics, percentile
from .migration import RequestSnapshot, advance_rng
from .queue import RequestQueue
from .request import (CLASS_BATCH, CLASS_INTERACTIVE, FINISH_EOS,
                      FINISH_LENGTH, FINISH_UNHEALTHY,
                      REJECT_ALL_REPLICAS_SATURATED, REJECT_DEGRADED,
                      REJECT_NO_FREE_BLOCKS, REJECT_PROMPT_TOO_LONG,
                      REJECT_QUEUE_FULL, REJECT_REPLICA_FAILED, Request,
                      RequestState, SamplingParams, TokenEvent, as_request)
from .router import Router, RouterMetrics
from .scheduler import ServingScheduler, simulate_static_batching
from .speculative import ModelDrafter, NgramDrafter

__all__ = [
    "ServingEngine",
    "ServingScheduler",
    "ServingMetrics",
    "RequestQueue",
    "Request",
    "RequestState",
    "SamplingParams",
    "TokenEvent",
    "VirtualClock",
    "WallClock",
    "as_request",
    "percentile",
    "simulate_static_batching",
    "KVPoolManager",
    "GARBAGE_BLOCK",
    "Router",
    "RouterMetrics",
    "Autoscaler",
    "BurnSensor",
    "DegradedModeController",
    "DEGRADED_LADDER",
    "CLASS_INTERACTIVE",
    "CLASS_BATCH",
    "NgramDrafter",
    "ModelDrafter",
    "RequestSnapshot",
    "advance_rng",
    "prefix_chain_keys",
    "FINISH_EOS",
    "FINISH_LENGTH",
    "FINISH_UNHEALTHY",
    "REJECT_QUEUE_FULL",
    "REJECT_PROMPT_TOO_LONG",
    "REJECT_NO_FREE_BLOCKS",
    "REJECT_ALL_REPLICAS_SATURATED",
    "REJECT_REPLICA_FAILED",
    "REJECT_DEGRADED",
]
