"""Paged KV-cache memory manager (host side of the block pool).

The dense slot pool allocates one ``n_slots x max_len`` KV region, so slot
count — i.e. concurrent users — is capped by WORST-CASE sequence length.
This module replaces that with vLLM-style paging, TPU-native by
construction: a fixed-shape pool of ``n_blocks`` physical token blocks plus
a per-slot block table. Everything dynamic lives HERE, on the host
(allocation, refcounts, the shared-prefix cache); the device only ever sees
static shapes — the decode program reads the pool through the traced block
table with gathers and still compiles exactly once.

Three mechanisms, one invariant set:

- **Block allocation by footprint.** A request reserves
  ``ceil((prompt + max_new - 1) / block_size)`` blocks — its actual token
  footprint — instead of a ``max_len`` window. Block 0 is the reserved
  GARBAGE block: freed slots' table rows point at it, so their dead decode
  writes can never corrupt a reallocated block.
- **Copy-on-write shared prefixes.** Full prompt blocks are
  content-addressed by an incremental SHA-256 chain over their token bytes
  (key_j commits to blocks 0..j; linear-time, collision-free in practice):
  an identical prefix maps to the SAME physical blocks,
  refcounted, and only the suffix is prefilled. Shared blocks are
  structurally read-only — a slot's write cursor starts at ``prompt_len``,
  and matching is capped at ``prompt_len - 1``, so the cursor can never
  enter a shared block. Cache entries hold their own +1 refcount and are
  evicted LRU when allocation needs the space.
- **Shed-with-reason.** A request whose footprint exceeds what the pool
  could EVER provide sheds ``no_free_blocks`` at admission; one that merely
  has to wait for running requests to free blocks stays queued (FCFS).

``stats()`` feeds ``ServingMetrics``' kv_pool block: occupancy (allocated /
allocatable blocks), internal fragmentation (1 - live tokens / allocated
token capacity), and the prefix hit rate (matched / candidate full blocks).
"""

import collections
import hashlib

from ..config.base import ConfigError

GARBAGE_BLOCK = 0


def prefix_chain_keys(prompt, block_size, limit=None):
    """``[((end, digest), end), ...]`` — one entry per full ``block_size``
    prompt block with ``end <= limit`` (default ``len(prompt)``). Keys are an
    INCREMENTAL SHA-256 chain over the token bytes (key_j digests blocks
    0..j), so key construction is linear in prompt length and a key still
    commits to the entire prefix content — two prompts share a key iff their
    prefixes collide SHA-256, i.e. never in practice.

    This is the cross-replica prefix currency: ``KVPoolManager`` content-
    addresses physical blocks with these keys, and the router's shared
    prefix index maps the SAME keys to replicas, so an identical system
    prompt routes to the replica whose pool already holds its blocks."""
    if limit is None:
        limit = len(prompt)
    out = []
    h = hashlib.sha256()
    end = block_size
    while end <= limit:
        h.update(prompt[end - block_size:end].tobytes())
        out.append(((end, h.digest()), end))
        end += block_size
    return out


class KVPoolManager:
    """Host-side allocator + prefix cache for the paged KV pool.

    Owns no device arrays: ``ServingEngine`` holds the pool/table state and
    calls back into this class for every allocation decision. All methods
    are O(blocks touched); nothing here is traced.
    """

    def __init__(self, cfg, n_slots, max_len):
        self.cfg = cfg
        self.block_size = int(cfg.block_size)
        if max_len % self.block_size:
            raise ConfigError(
                f"serving max_len {max_len} must be a multiple of "
                f"kv_pool.block_size {self.block_size}")
        self.blocks_per_slot = max_len // self.block_size
        auto = n_slots * self.blocks_per_slot + 1
        self.n_blocks = int(cfg.n_blocks) or auto
        if self.n_blocks < 2:
            raise ConfigError(
                f"kv_pool.n_blocks must be >= 2 (block 0 is reserved), "
                f"got {self.n_blocks}")
        self._free = collections.deque(range(1, self.n_blocks))
        self._ref = [0] * self.n_blocks
        # prefix cache: token-bytes key -> physical block id (LRU order);
        # each cached block carries its own +1 ref so it survives request
        # churn until evicted
        self._prefix = collections.OrderedDict()
        self._block_key = {}        # block id -> its cache key (if cached)
        self._slot_blocks = {}      # slot -> list of distinct block ids
        self._slot_tokens = {}      # slot -> footprint in tokens (live)
        # counters (prefix hit rate is per candidate FULL block, the unit
        # sharing actually happens at)
        self.prefix_hit_blocks = 0
        self.prefix_candidate_blocks = 0
        self.prefix_hit_requests = 0
        self.prefix_requests = 0
        self.scrubbed_blocks = 0
        self.grown_blocks = 0       # on-demand-growth allocations mid-decode
        self.preempted_requests = 0  # preempt-to-queue on pool exhaustion
        # speculative rollback: grown blocks released because every row
        # they held belonged to rejected draft candidates
        self.rolled_back_blocks = 0
        self._scrub = None          # engine-installed per-block scrub hook
        # admission-time reservations not yet consumed by a slot insert:
        # chunked prefill opens a multi-step window between can_admit and
        # insert, and a later admission must not steal the head's blocks
        self._pending = 0

    # -- capacity ----------------------------------------------------------
    @property
    def allocatable(self):
        """Blocks a single request could ever hold (garbage block excluded)."""
        return self.n_blocks - 1

    def blocks_for(self, prompt_len, max_new_tokens):
        """Footprint of a request: positions [0, prompt_len + max_new - 1)
        are written (the last sampled token is never written back)."""
        tokens = max(prompt_len + max_new_tokens - 1, 1)
        return -(-tokens // self.block_size)

    def blocks_for_prefill(self, prefill_len):
        """On-demand growth's ADMISSION footprint: only the prefilled
        positions [0, prefill_len) — decode blocks are allocated as the
        cursor advances (``reserve-as-you-decode``), so admission stops
        paying for tokens not yet generated."""
        return -(-max(prefill_len, 1) // self.block_size)

    def _evictable(self):
        """Cached prefix blocks held ONLY by the cache (ref == 1)."""
        return sum(1 for b in self._prefix.values() if self._ref[b] == 1)

    def can_allocate(self, n):
        return n + self._pending <= len(self._free) + self._evictable()

    # -- admission reservations -------------------------------------------
    def reserve(self, n):
        """Hold ``n`` blocks against future ``can_allocate`` checks until a
        slot insert consumes the reservation (chunked prefill runs between
        admission and insert; without this, a later admission or an
        on-demand growth could strand the admitted head)."""
        self._pending += int(n)

    def consume_reservation(self, n):
        """The insert that the reservation guarded is allocating now."""
        self._pending = max(self._pending - int(n), 0)

    def fits_ever(self, prompt_len, max_new_tokens):
        """False -> shed ``no_free_blocks``: even an empty pool could not
        hold this request's footprint."""
        return self.blocks_for(prompt_len, max_new_tokens) <= self.allocatable

    # -- allocation --------------------------------------------------------
    def alloc(self, n):
        """Take ``n`` free blocks (evicting LRU cached prefixes as needed).
        Returns the block ids; raises if ``can_allocate(n)`` was False."""
        while len(self._free) < n and self._evict_one():
            pass
        if len(self._free) < n:
            raise RuntimeError(
                f"kv_pool: asked for {n} blocks with only {len(self._free)} "
                "free and nothing evictable (caller skipped can_allocate)")
        out = [self._free.popleft() for _ in range(n)]
        for b in out:
            self._ref[b] += 1
        return out

    def _evict_one(self):
        """Drop the LRU prefix entry whose block the cache holds the LAST
        reference to (ref == 1) — evicting an entry a running slot still
        references would free nothing while destroying shareable cache
        state for good. Returns False when nothing evictable remains."""
        for key, bid in self._prefix.items():
            if self._ref[bid] == 1:
                del self._prefix[key]
                del self._block_key[bid]
                self._unref(bid)
                return True
        return False

    def _unref(self, bid):
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)
            if self._scrub is not None:
                self._scrub(bid)
                self.scrubbed_blocks += 1

    def release_blocks(self, block_ids):
        """Drop one reference per distinct block (early-finish / error
        unwind for blocks not yet bound to a slot)."""
        for b in dict.fromkeys(block_ids):
            if b != GARBAGE_BLOCK:
                self._unref(b)

    # -- slot binding ------------------------------------------------------
    def bind_slot(self, slot, block_ids, footprint_tokens):
        """Record ``slot`` as owning ``block_ids`` (refs were taken by
        ``alloc``/``acquire_prefix``)."""
        self._slot_blocks[slot] = list(dict.fromkeys(
            b for b in block_ids if b != GARBAGE_BLOCK))
        self._slot_tokens[slot] = int(footprint_tokens)

    def free_slot(self, slot):
        """Release every block the slot holds; a block returns to the free
        list (and is scrubbed, if configured) when its last reference —
        slot or prefix-cache — drops."""
        for b in self._slot_blocks.pop(slot, ()):
            self._unref(b)
        self._slot_tokens.pop(slot, None)

    def grow_slot(self, slot, live_tokens):
        """On-demand growth: allocate ONE more block for ``slot`` (its decode
        cursor reached the end of its bound blocks) and record it. Returns
        the physical block id; the caller must have checked
        ``can_allocate(1)`` (and preempts to the queue when it is False)."""
        bid = self.alloc(1)[0]
        self._slot_blocks[slot].append(bid)
        self._slot_tokens[slot] = int(live_tokens)
        self.grown_blocks += 1
        return bid

    def shrink_slot(self, slot, live_tokens):
        """Speculative rollback under on-demand growth: drop the slot's
        LAST bound block — it lies entirely past the rolled-back cursor,
        so every row it holds belongs to rejected draft candidates. The
        block returns to the allocator on its last-ref drop (and is
        scrubbed there when the hygiene scrub is armed); the caller must
        already have retreated the slot's table entry to the garbage
        block."""
        bid = self._slot_blocks[slot].pop()
        self._slot_tokens[slot] = int(live_tokens)
        self.rolled_back_blocks += 1
        self._unref(bid)

    def slot_block_count(self, slot):
        return len(self._slot_blocks.get(slot, ()))

    def slot_block(self, slot, j):
        """Physical block id at table column ``j`` of ``slot``."""
        return self._slot_blocks[slot][j]

    # -- shared prefixes ---------------------------------------------------
    def _candidate_keys(self, prompt, limit):
        """(key, end) per full prompt block with ``end <= limit`` (the
        module-level ``prefix_chain_keys`` chain — shared with the router's
        cross-replica prefix index so both sides speak the same keys)."""
        return prefix_chain_keys(prompt, self.block_size, limit)

    def acquire_prefix(self, prompt):
        """Longest cached prefix of ``prompt``: returns (shared_len,
        block_ids), taking one reference per matched block (so an eviction
        between admission and insert cannot dangle them). Counters feed the
        prefix_hit_rate metric."""
        if not self.cfg.prefix_cache:
            return 0, []
        # capped at prompt_len - 1 so the write cursor (>= prompt_len) can
        # never enter a matched block — COW holds structurally, no device
        # fault path needed
        cands = self._candidate_keys(prompt, len(prompt) - 1)
        if cands:
            self.prefix_requests += 1
        self.prefix_candidate_blocks += len(cands)
        blocks, shared_len = [], 0
        for key, end in cands:
            bid = self._prefix.get(key)
            if bid is None:
                break
            self._prefix.move_to_end(key)   # LRU recency
            self._ref[bid] += 1
            blocks.append(bid)
            shared_len = end
        self.prefix_hit_blocks += len(blocks)
        if blocks:
            self.prefix_hit_requests += 1
        return shared_len, blocks

    def register_prefix(self, prompt, table_blocks):
        """Content-address the request's full prompt blocks (block j is
        full iff (j+1)*block_size <= prompt_len; such blocks are never
        written after insert, so sharing them is safe). Already-cached keys
        keep their canonical block; new ones take the cache's +1 ref."""
        if not self.cfg.prefix_cache:
            return
        bs = self.block_size
        limit = min(len(prompt) // bs, len(table_blocks)) * bs
        for j, (key, _end) in enumerate(self._candidate_keys(prompt, limit)):
            if key in self._prefix:
                self._prefix.move_to_end(key)
                continue
            bid = table_blocks[j]
            if bid == GARBAGE_BLOCK or bid in self._block_key:
                continue
            self._ref[bid] += 1
            self._prefix[key] = bid
            self._block_key[bid] = key

    # -- metrics -----------------------------------------------------------
    def occupancy(self):
        """Held fraction of allocatable blocks — the cheap O(1) accessor
        the router's per-request load scoring reads; the full ``stats()``
        dict (with its per-slot scans) is for metrics emission."""
        allocatable = max(self.allocatable, 1)
        return (allocatable - len(self._free)) / allocatable

    def stats(self):
        allocatable = max(self.allocatable, 1)
        held = allocatable - len(self._free)   # slots + prefix cache
        occupancy = self.occupancy()
        live_tokens = sum(self._slot_tokens.values())
        slot_capacity = sum(len(b) for b in self._slot_blocks.values()) \
            * self.block_size
        return {
            "n_blocks": self.n_blocks,
            "block_size": self.block_size,
            "capacity_tokens": allocatable * self.block_size,
            "allocated_blocks": held,
            "free_blocks": len(self._free),
            "cached_prefix_blocks": len(self._prefix),
            "occupancy": round(occupancy, 4),
            # internal fragmentation of REQUEST-held blocks: reserved token
            # capacity the live footprints don't use (0 = perfectly packed)
            "fragmentation": round(1.0 - live_tokens / slot_capacity, 4)
            if slot_capacity else 0.0,
            "prefix_hit_rate": round(
                self.prefix_hit_blocks / self.prefix_candidate_blocks, 4)
            if self.prefix_candidate_blocks else 0.0,
            "prefix_hit_requests": self.prefix_hit_requests,
            "prefix_requests": self.prefix_requests,
            "scrubbed_blocks": self.scrubbed_blocks,
            "grown_blocks": self.grown_blocks,
            "preempted_requests": self.preempted_requests,
            "rolled_back_blocks": self.rolled_back_blocks,
            "reserved_blocks": self._pending,
        }
