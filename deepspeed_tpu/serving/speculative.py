"""Speculative decoding drafters (the proposal half of the subsystem).

Classic speculative decoding (arXiv:2211.17192) on the serving stack: a
cheap DRAFTER proposes up to ``k`` continuation tokens per greedy slot, the
target model verifies all of them in ONE forward over k+1 positions against
the paged KV cache (``models/decoding.py:verify_with_paged_cache``, wired
into the slot pool by ``serving/engine.py``), and the longest prefix whose
drafts equal the target's own argmax is accepted. Everything accepted IS
the target's greedy stream — the drafter only decides how many tokens each
dispatch may yield, never which tokens, so greedy parity with ``generate()``
holds for ANY drafter (tier-1 pins it, including a deliberately-wrong one).

Two drafters:

- **NgramDrafter** (prompt lookup, zero extra weights): match the last
  ``ngram`` tokens of the request's own prompt+generated history against
  earlier history and propose the continuation of the most recent match.
  Free, host-side, and strong exactly where speculation pays — repetitive
  spans (quotes, code, structured output, cycles).
- **ModelDrafter**: a small draft model sharing the target's mesh (separate
  params, its own tiny dense per-slot KV cache). Proposals run as one
  jitted k-step scan; history catch-up (tokens the target emitted since the
  last proposal) feeds through one single-token program. Both programs
  compile exactly once (tier-1 pins the census); proposal rows written past
  the synced cursor are overwritten by the next catch-up, so a rejected
  draft path needs no device-side rollback here either.

The drafter interface is deliberately host-level: ``propose`` sees each
slot's full token history and returns candidate arrays. The engine owns
eligibility (greedy slots only, tokens still owed, block coverage) and all
acceptance/rollback bookkeeping.
"""

import numpy as np

_EMPTY = np.zeros((0,), np.int32)


class Drafter:
    """Interface. ``propose`` maps ``{slot: (history, cap)}`` — history =
    prompt + every generated token, cap = max useful candidates — to
    ``{slot: np.ndarray[int32]}`` (slots with nothing to propose omitted).
    ``release`` is called whenever a slot stops running (finish, preempt,
    unhealthy shed) so stateful drafters drop/resync their per-slot state.
    """

    name = "?"

    def propose(self, wanted):
        raise NotImplementedError

    def release(self, slot):
        pass

    def compile_counts(self):
        return {}


class NgramDrafter(Drafter):
    """Prompt-lookup drafting: the request's own history is the draft model.

    Deterministic and stateless — a preemption/resume or a mid-run
    speculation toggle cannot perturb anything, because there is nothing
    to perturb."""

    name = "ngram"

    def __init__(self, cfg):
        self.n = int(cfg.ngram)
        self.k = int(cfg.k)

    def propose(self, wanted):
        out = {}
        for slot, (hist, cap) in wanted.items():
            d = self._lookup(np.asarray(hist, np.int64), min(cap, self.k))
            if d.size:
                out[slot] = d
        return out

    def _lookup(self, hist, cap):
        n = self.n
        if cap < 1 or len(hist) < n + 2:
            return _EMPTY
        pattern = hist[-n:]
        # windows over hist[:-1]: every match has >= 1 continuation token,
        # and the trailing occurrence of the pattern itself is excluded
        windows = np.lib.stride_tricks.sliding_window_view(hist[:-1], n)
        idx = np.flatnonzero(np.all(windows == pattern[None, :], axis=1))
        if idx.size == 0:
            return _EMPTY
        i = int(idx[-1])  # most recent earlier occurrence
        return hist[i + n:i + n + cap].astype(np.int32)


class ModelDrafter(Drafter):
    """Draft-model drafting: a small transformer sharing the target's mesh.

    Separate params (``speculative.draft_model`` TransformerConfig
    overrides over a 1-layer copy of the target; vocab/max_seq_len pinned),
    a dense per-slot KV cache of its own, and a host-side per-slot cursor
    ``_pos`` = history positions ingested. Catch-up (history the target
    emitted since the last proposal, or the whole prompt at a slot's first
    proposal) feeds through ONE multi-token ingest program —
    ``INGEST_BLOCK`` positions per dispatch, so a fresh long prompt costs
    O(len / block) dispatches, not O(len). A proposal then feeds the last
    history token at the cursor and scans k argmax steps WITHOUT advancing
    the cursor — the speculated rows are overwritten by the next catch-up
    (accepted tokens re-feed the same positions; the causal mask hides the
    rest), which is the draft-side rollback for free."""

    name = "model"
    # catch-up tokens fed per ingest dispatch (shapes the ingest program;
    # per-slot shortfall pads with dead writes at positions the slot will
    # overwrite at its own next real feed)
    INGEST_BLOCK = 32

    def __init__(self, serving):
        import dataclasses

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..models.decoding import forward_with_cache, init_cache
        from ..models.layers import Param, split_params_axes
        from ..models.transformer import CausalLM
        from ..parallel import MODEL_AXIS
        from ..parallel.sharding import named, param_partition_specs

        engine = serving.engine
        cfg = serving.cfg.speculative
        self.k = int(cfg.k)
        self.n_slots = serving.n_slots
        self.max_len = serving.max_len
        tgt = engine.module.config
        overrides = dict(cfg.draft_model or {})
        # vocab and position space MUST match the target: drafts are target
        # token ids at target positions
        overrides.pop("vocab_size", None)
        overrides.pop("max_seq_len", None)
        dcfg = dataclasses.replace(tgt, n_layers=1, **overrides)
        self.model = CausalLM(dcfg)
        mesh = engine.mesh
        rng = jax.random.PRNGKey(int(cfg.draft_seed))
        params_shape = jax.eval_shape(self.model.init, rng)
        axes = jax.tree_util.tree_map(
            lambda p: p.axes if isinstance(p, Param)
            else (None,) * len(p.shape),
            params_shape, is_leaf=lambda x: isinstance(x, Param))
        shapes = jax.tree_util.tree_map(
            lambda p: tuple((p.value if isinstance(p, Param) else p).shape),
            params_shape, is_leaf=lambda x: isinstance(x, Param))
        specs = param_partition_specs(axes, shapes, mesh, zero_stage=0)
        shardings = named(mesh, specs)
        init_fn = lambda r: jax.tree_util.tree_map(
            lambda a: (a.value if isinstance(a, Param) else a)
            .astype(engine.dtype),
            self.model.init(r), is_leaf=lambda x: isinstance(x, Param))
        with mesh:
            self.params = jax.jit(init_fn, out_shardings=shardings)(rng)
        kv_axis = MODEL_AXIS if dcfg.kv_heads % max(engine.mp_world_size,
                                                    1) == 0 else None
        cache_sharding = NamedSharding(mesh, P(None, None, None, kv_axis,
                                               None))
        rep = NamedSharding(mesh, P())
        self._cache = jax.device_put(
            init_cache(dcfg, self.n_slots, self.max_len, engine.dtype),
            {"k": cache_sharding, "v": cache_sharding})
        self._pos = np.zeros((self.n_slots,), np.int64)

        model, max_len, k = self.model, self.max_len, self.k

        def ingest(params, cache, toks, pos):
            # catch-up: INGEST_BLOCK tokens per slot at its draft cursor,
            # one dispatch. Per-slot shortfall/idle rows write garbage at
            # positions their own next real feed overwrites; the reverse
            # row order keeps window-clamped pad writes from shadowing a
            # real row (same discipline as the verify program)
            _, cache = forward_with_cache(model, params, toks, cache, pos,
                                          max_len, row_writes="reverse")
            return cache

        def propose(params, cache, tok, pos):
            # k argmax steps as ONE dispatch; cursor advance is in-graph
            # only — the host cursor stays at the synced point, so the
            # speculated rows are rolled back by simply being overwritten
            def step(carry, _):
                cache, tok, pos = carry
                logits, cache = forward_with_cache(
                    model, params, tok[:, None], cache, pos, max_len)
                nxt = jnp.argmax(logits[:, 0].astype(jnp.float32),
                                 axis=-1).astype(jnp.int32)
                return (cache, nxt, pos + 1), nxt

            (cache, _, _), toks = jax.lax.scan(step, (cache, tok, pos),
                                               length=k)
            return jnp.transpose(toks), cache  # [S, k]

        with mesh:
            self._ingest_jit = jax.jit(
                ingest, donate_argnums=(1,),
                out_shardings={"k": cache_sharding, "v": cache_sharding})
            self._propose_jit = jax.jit(
                propose, donate_argnums=(1,),
                out_shardings=(rep, {"k": cache_sharding,
                                     "v": cache_sharding}))

    def release(self, slot):
        # resync from scratch at the slot's next proposal: the cache rows
        # are stale-but-masked, the cursor reset makes them unreachable
        # until overwritten
        self._pos[slot] = 0

    def propose(self, wanted):
        import jax.numpy as jnp

        ib = self.INGEST_BLOCK
        # catch-up rounds: INGEST_BLOCK tokens per dispatch until every
        # wanted slot has ingested history[:-1] (usually one round of 1-k
        # tokens — what the target emitted since the last proposal; a
        # slot's FIRST proposal ingests its whole prompt in len/IB rounds)
        while True:
            feed = np.zeros((self.n_slots, ib), np.int32)
            counts = np.zeros((self.n_slots,), np.int64)
            for slot, (hist, _cap) in wanted.items():
                pending = hist[self._pos[slot]:len(hist) - 1][:ib]
                feed[slot, :len(pending)] = pending
                counts[slot] = len(pending)
            if not counts.any():
                break
            self._cache = self._ingest_jit(
                self.params, self._cache, jnp.asarray(feed),
                jnp.asarray(self._pos, jnp.int32))
            self._pos += counts
        tok = np.zeros((self.n_slots,), np.int32)
        for slot, (hist, _cap) in wanted.items():
            tok[slot] = hist[-1]
        toks, self._cache = self._propose_jit(
            self.params, self._cache, jnp.asarray(tok),
            jnp.asarray(self._pos, jnp.int32))
        toks = np.asarray(toks)
        out = {}
        for slot, (_hist, cap) in wanted.items():
            cap = min(cap, self.k)
            if cap > 0:
                out[slot] = toks[slot, :cap].astype(np.int32)
        return out

    def compile_counts(self):
        size = lambda f: f._cache_size() if f is not None else 0
        return {"draft_ingest": size(self._ingest_jit),
                "draft_propose": size(self._propose_jit)}

    def destroy(self):
        self.params = None
        self._cache = None
        self._ingest_jit = None
        self._propose_jit = None


def build_drafter(serving):
    """Drafter for the serving engine's ``serving.speculative`` block."""
    cfg = serving.cfg.speculative
    if cfg.drafter == "model":
        return ModelDrafter(serving)
    return NgramDrafter(cfg)
