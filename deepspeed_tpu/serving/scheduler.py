"""Continuous-batching scheduler policy (the Orca-style iteration loop).

``ServingScheduler`` decides, at each scheduler step, which queued requests to
prefill into free decode slots — FCFS, with at most ``max_prefills_per_step``
prefills interleaved per step so an arrival burst can't starve running
decodes (TPOT protection). The device-side mechanics (prefill, slot insert,
decode step) live in ``serving/engine.py``; this module is pure host policy,
so it is exactly simulable under the virtual clock.

``hol_bypass_limit`` relaxes strict FCFS under block-aware admission: when
the queue head's KV footprint cannot fit but a later request's can, up to
``limit`` later requests may be admitted past the stuck head before
admissions stop until the head clears — work keeps flowing without unbounded
starvation of the big request. 0 (the default) preserves strict FCFS.

``simulate_static_batching`` is the baseline the continuous scheduler is
measured against in tier-1: classic whole-batch serving, where a batch of
``n_slots`` requests decodes until its LONGEST member finishes before any new
request starts. The shared virtual cost model (decode step / prefill token)
makes the comparison apples-to-apples.
"""


class ServingScheduler:
    """FCFS admission from the bounded queue into free slots."""

    def __init__(self, queue, n_slots, max_prefills_per_step=1,
                 policy="fcfs", hol_bypass_limit=0):
        if policy != "fcfs":
            raise ValueError(f"unknown scheduling policy {policy!r}")
        self.queue = queue
        self.n_slots = n_slots
        self.max_prefills_per_step = max(int(max_prefills_per_step), 1)
        self.hol_bypass_limit = max(int(hol_bypass_limit), 0)
        # bounded-starvation window: how many requests have overtaken the
        # CURRENT stuck head (reset whenever the head is admitted/replaced)
        self._hol_head = None
        self._hol_bypasses = 0

    def next_admissions(self, free_slots, now, can_admit=None):
        """Requests to prefill this step: bounded by free slots AND the
        per-step prefill cap. ``now`` gates open-loop arrivals that were
        queued with a future arrival_time (virtual-clock simulations).

        ``can_admit``: optional capacity predicate (the paged KV pool's
        block-availability check). A head it rejects WAITS at the front —
        FCFS — unless ``hol_bypass_limit`` grants a later arrived-and-
        fitting request one of its bounded bypass slots."""
        out = []
        budget = min(free_slots, self.max_prefills_per_step)
        while budget > 0 and len(self.queue):
            head = self.queue.peek()
            if head.arrival_time is not None and head.arrival_time > now:
                break  # FCFS: nothing behind it may jump a not-yet-arrival
            if can_admit is not None and not can_admit(head):
                bypassed = self._try_bypass(now, can_admit)
                if bypassed is None:
                    break  # hold the line until running requests free blocks
                out.append(bypassed)
                budget -= 1
                continue
            if self._hol_head == head.request_id:
                # the stuck head finally fits: its starvation window closes
                self._hol_head = None
                self._hol_bypasses = 0
            out.append(self.queue.pop())
            budget -= 1
        for req in out:
            # admission stamp for the wide-event queue-wait breakdown; a
            # preemption-resume re-admission keeps the ORIGINAL stamp (its
            # queue-wait window closed at the first prefill)
            if req.admit_time is None:
                req.admit_time = now
        return out

    def _try_bypass(self, now, can_admit):
        """One bounded-starvation bypass of a blocked head, or None.

        The window is per stuck head: once ``hol_bypass_limit`` requests have
        overtaken it, nothing more is admitted until the head itself clears
        (so the big request is delayed by at most ``limit`` overtakers, not
        forever). The caller's ``can_admit`` carries the reservation
        counter, so a granted bypass reserves its blocks exactly like a
        head admission would."""
        if self.hol_bypass_limit <= 0:
            return None
        head = self.queue.peek()
        if self._hol_head != head.request_id:
            self._hol_head = head.request_id
            self._hol_bypasses = 0
        if self._hol_bypasses >= self.hol_bypass_limit:
            return None
        for i in range(1, len(self.queue)):
            cand = self.queue.peek_at(i)
            if cand.arrival_time is not None and cand.arrival_time > now:
                break  # arrivals are time-ordered; nothing further is due
            if can_admit(cand):
                self._hol_bypasses += 1
                return self.queue.pop_at(i)
        return None


def simulate_static_batching(requests, n_slots, *, prefill_cost_per_token,
                             decode_step_cost, bucket_len):
    """Virtual cost of serving ``requests`` with static whole-batch batching.

    Requests are grouped FCFS into batches of ``n_slots``. Each batch pays
    one bucketed-prompt prefill (the batch pads to its longest prompt bucket,
    like a fixed-shape ``generate()`` call) plus ``max(max_new_tokens) - 1``
    decode steps — every short request idles its slot until the longest
    member finishes, which is exactly the utilization gap continuous batching
    closes. Returns ``(total_tokens, virtual_time)``.
    """
    total_tokens = 0
    t = 0.0
    reqs = list(requests)
    for i in range(0, len(reqs), n_slots):
        batch = reqs[i:i + n_slots]
        padded = max(bucket_len(r.prompt_len) for r in batch)
        # one batched prefill (generously: no extra cost for the extra rows),
        # whose logits yield every request's FIRST token — then decode steps
        # until the longest member is done
        t += padded * prefill_cost_per_token
        t += max(r.max_new_tokens - 1 for r in batch) * decode_step_cost
        total_tokens += sum(r.max_new_tokens for r in batch)
    return total_tokens, t
