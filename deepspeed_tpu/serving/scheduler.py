"""Continuous-batching scheduler policy (the Orca-style iteration loop).

``ServingScheduler`` decides, at each scheduler step, which queued requests to
prefill into free decode slots — FCFS, with at most ``max_prefills_per_step``
prefills interleaved per step so an arrival burst can't starve running
decodes (TPOT protection). The device-side mechanics (prefill, slot insert,
decode step) live in ``serving/engine.py``; this module is pure host policy,
so it is exactly simulable under the virtual clock.

``hol_bypass_limit`` relaxes strict FCFS under block-aware admission: when
the queue head's KV footprint cannot fit but a later request's can, up to
``limit`` later requests may be admitted past the stuck head before
admissions stop until the head clears — work keeps flowing without unbounded
starvation of the big request. 0 (the default) preserves strict FCFS.

``policy="weighted_fair"`` (serving.tenants) replaces global FCFS with
start-time fair queuing (SFQ) across tenants: every admission charges its
token cost against the tenant's virtual-finish tag at ``cost / weight``, and
the queued request with the LOWEST start tag wins the next slot — so over
any busy interval each tenant's admitted tokens converge to its weight
share, while a tenant alone in the queue still gets every slot
(work-conserving). Per-tenant token buckets (``token_budget_per_s`` /
``token_budget_burst``) gate admission exactly under the virtual clock;
an over-budget tenant is DEFERRED, never shed. The FCFS head-of-line
bypass generalizes naturally: a winner blocked by the capacity predicate
keeps its low tag and is overtaken for one step by the next-best tenant's
candidate — bounded by construction, one candidate per tenant per step.

``simulate_static_batching`` is the baseline the continuous scheduler is
measured against in tier-1: classic whole-batch serving, where a batch of
``n_slots`` requests decodes until its LONGEST member finishes before any new
request starts. The shared virtual cost model (decode step / prefill token)
makes the comparison apples-to-apples.
"""


class ServingScheduler:
    """FCFS admission from the bounded queue into free slots."""

    def __init__(self, queue, n_slots, max_prefills_per_step=1,
                 policy="fcfs", hol_bypass_limit=0, tenants=None):
        if policy not in ("fcfs", "weighted_fair"):
            raise ValueError(f"unknown scheduling policy {policy!r}")
        self.queue = queue
        self.n_slots = n_slots
        self.policy = policy
        self.tenants = tenants
        self.max_prefills_per_step = max(int(max_prefills_per_step), 1)
        self.hol_bypass_limit = max(int(hol_bypass_limit), 0)
        # bounded-starvation window: how many requests have overtaken the
        # CURRENT stuck head (reset whenever the head is admitted/replaced)
        self._hol_head = None
        self._hol_bypasses = 0
        # weighted-fair state: global virtual time, per-tenant virtual
        # finish tags, per-tenant token buckets (tokens, last_refill_t)
        self._vnow = 0.0
        self._vfinish = {}
        self._buckets = {}

    def next_admissions(self, free_slots, now, can_admit=None):
        if self.policy == "weighted_fair":
            return self._fair_admissions(free_slots, now, can_admit)
        return self._fcfs_admissions(free_slots, now, can_admit)

    def _fcfs_admissions(self, free_slots, now, can_admit=None):
        """Requests to prefill this step: bounded by free slots AND the
        per-step prefill cap. ``now`` gates open-loop arrivals that were
        queued with a future arrival_time (virtual-clock simulations).

        ``can_admit``: optional capacity predicate (the paged KV pool's
        block-availability check). A head it rejects WAITS at the front —
        FCFS — unless ``hol_bypass_limit`` grants a later arrived-and-
        fitting request one of its bounded bypass slots."""
        out = []
        budget = min(free_slots, self.max_prefills_per_step)
        while budget > 0 and len(self.queue):
            head = self.queue.peek()
            if head.arrival_time is not None and head.arrival_time > now:
                break  # FCFS: nothing behind it may jump a not-yet-arrival
            if can_admit is not None and not can_admit(head):
                bypassed = self._try_bypass(now, can_admit)
                if bypassed is None:
                    break  # hold the line until running requests free blocks
                out.append(bypassed)
                budget -= 1
                continue
            if self._hol_head == head.request_id:
                # the stuck head finally fits: its starvation window closes
                self._hol_head = None
                self._hol_bypasses = 0
            out.append(self.queue.pop())
            budget -= 1
        for req in out:
            # admission stamp for the wide-event queue-wait breakdown; a
            # preemption-resume re-admission keeps the ORIGINAL stamp (its
            # queue-wait window closed at the first prefill)
            if req.admit_time is None:
                req.admit_time = now
        return out

    def _try_bypass(self, now, can_admit):
        """One bounded-starvation bypass of a blocked head, or None.

        The window is per stuck head: once ``hol_bypass_limit`` requests have
        overtaken it, nothing more is admitted until the head itself clears
        (so the big request is delayed by at most ``limit`` overtakers, not
        forever). The caller's ``can_admit`` carries the reservation
        counter, so a granted bypass reserves its blocks exactly like a
        head admission would."""
        if self.hol_bypass_limit <= 0:
            return None
        head = self.queue.peek()
        if self._hol_head != head.request_id:
            self._hol_head = head.request_id
            self._hol_bypasses = 0
        if self._hol_bypasses >= self.hol_bypass_limit:
            return None
        for i in range(1, len(self.queue)):
            cand = self.queue.peek_at(i)
            if cand.arrival_time is not None and cand.arrival_time > now:
                break  # arrivals are time-ordered; nothing further is due
            if can_admit(cand):
                self._hol_bypasses += 1
                return self.queue.pop_at(i)
        return None

    # -- weighted-fair admission (policy="weighted_fair") --------------------

    def _class_cfg(self, req):
        if self.tenants is None:
            return None
        return self.tenants.class_config(req.tenant_class)

    def _weight(self, req):
        cfg = self._class_cfg(req)
        return cfg.weight if cfg is not None else 1.0

    @staticmethod
    def _cost(req):
        """An admission's fair-share cost: the KV/compute footprint it may
        claim — prompt plus the full generation budget it reserved."""
        return float(req.prompt_len + req.max_new_tokens)

    def _bucket(self, req, now):
        """This tenant's token bucket, refilled to ``now``; None when the
        tenant has no budget configured. Refill is rate * elapsed virtual
        time, capped at burst — exact under the virtual clock."""
        cfg = self._class_cfg(req)
        if cfg is None or cfg.token_budget_per_s <= 0:
            return None
        burst = cfg.token_budget_burst or cfg.token_budget_per_s
        tokens, last = self._buckets.get(req.tenant_id, (burst, now))
        tokens = min(burst, tokens
                     + cfg.token_budget_per_s * max(now - last, 0.0))
        self._buckets[req.tenant_id] = (tokens, now)
        return tokens, burst

    def budget_ok(self, req, now):
        """Would the tenant's token bucket admit this request now? A request
        costing more than the burst is gated on a FULL bucket and runs the
        bucket into arrears — budgets defer admission, they never shed."""
        b = self._bucket(req, now)
        if b is None:
            return True
        tokens, burst = b
        return tokens + 1e-9 >= min(self._cost(req), burst)

    def charge(self, req, now):
        """Account one admission: deduct the token budget (arrears allowed)
        and advance the tenant's SFQ virtual-finish tag. Also the direct-
        admission hook for the engine's priority-preemption path. A resumed
        request (admit_time already stamped) was charged at its FIRST
        admission — a preemption must not double-bill the tenant."""
        if req.admit_time is not None:
            return
        cost = self._cost(req)
        b = self._bucket(req, now)
        if b is not None:
            tokens, _ = b
            self._buckets[req.tenant_id] = (tokens - cost, now)
        start = max(self._vfinish.get(req.tenant_id, 0.0), self._vnow)
        self._vnow = start
        self._vfinish[req.tenant_id] = start + cost / self._weight(req)
        req.admit_time = now

    def _fair_admissions(self, free_slots, now, can_admit):
        out = []
        budget = min(free_slots, self.max_prefills_per_step)
        while budget > 0 and len(self.queue):
            picked = self._fair_pick(now, can_admit)
            if picked is None:
                break
            out.append(picked)
            budget -= 1
        return out

    def _fair_pick(self, now, can_admit):
        """One SFQ selection, or None when nothing is eligible.

        Preemption returners outrank fresh arrivals in queue order (they
        hold their original seniority — ``push_front`` put them at the
        head). Among fresh arrivals, each tenant fields its OLDEST
        budget-eligible request, ordered by SFQ start tag (ties broken by
        arrival order). ``can_admit`` — the paged pool's reserving
        capacity predicate — is consulted only on would-be winners, in
        tag order: a blocked winner keeps its low tag and is overtaken
        for this step only, the fair-queue form of the bounded HOL
        bypass. Start tags are floored at the global virtual time, so a
        tenant idle through a busy interval re-enters at the frontier —
        weights share the BUSY intervals, they don't bank idle credit."""
        returners = []   # queue indices, in order
        fresh = {}       # tenant_id -> (start_tag, queue index)
        for i in range(len(self.queue)):
            cand = self.queue.peek_at(i)
            if cand.arrival_time is not None and cand.arrival_time > now:
                break  # arrivals are time-ordered; nothing further is due
            if cand.admit_time is not None:
                returners.append(i)
                continue
            if cand.tenant_id in fresh:
                continue  # within-tenant order stays strict FCFS
            if not self.budget_ok(cand, now):
                continue  # over budget: the tenant is deferred this step
            start = max(self._vfinish.get(cand.tenant_id, 0.0), self._vnow)
            fresh[cand.tenant_id] = (start, i)
        for i in returners + [i for _, i in sorted(fresh.values())]:
            cand = self.queue.peek_at(i)
            if can_admit is None or can_admit(cand):
                req = self.queue.pop_at(i)
                self.charge(req, now)
                return req
        return None


def simulate_static_batching(requests, n_slots, *, prefill_cost_per_token,
                             decode_step_cost, bucket_len):
    """Virtual cost of serving ``requests`` with static whole-batch batching.

    Requests are grouped FCFS into batches of ``n_slots``. Each batch pays
    one bucketed-prompt prefill (the batch pads to its longest prompt bucket,
    like a fixed-shape ``generate()`` call) plus ``max(max_new_tokens) - 1``
    decode steps — every short request idles its slot until the longest
    member finishes, which is exactly the utilization gap continuous batching
    closes. Returns ``(total_tokens, virtual_time)``.
    """
    total_tokens = 0
    t = 0.0
    reqs = list(requests)
    for i in range(0, len(reqs), n_slots):
        batch = reqs[i:i + n_slots]
        padded = max(bucket_len(r.prompt_len) for r in batch)
        # one batched prefill (generously: no extra cost for the extra rows),
        # whose logits yield every request's FIRST token — then decode steps
        # until the longest member is done
        t += padded * prefill_cost_per_token
        t += max(r.max_new_tokens - 1 for r in batch) * decode_step_cost
        total_tokens += sum(r.max_new_tokens for r in batch)
    return total_tokens, t
