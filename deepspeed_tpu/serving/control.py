"""SLO-driven serving control plane: autoscaling + degraded-mode policy.

PR 13 built the fleet's sensors (streaming log-bucket latency digests,
``slo_burn_rate``, goodput, wide events) and the disaggregated-fleet work
built the actuator surface (pooled replicas, drain(migrate=True)/rejoin,
live migration, rebalance). This module closes the control loop:

- :class:`BurnSensor` — the WINDOWED burn rate a controller actually needs:
  the fraction-over-target of the samples added since the previous
  evaluation, against the 1% error budget a P99 objective grants. The
  cumulative digest burn (``evaluate_slo``) is an ever-growing average —
  fine for grading a run, useless for reacting to a burst mid-run.
- :class:`Autoscaler` — scales the Router's ACTIVE replica set between
  ``autoscaler.min_replicas`` and the constructed fleet size through the
  existing drain/rejoin lifecycle, per pool when ``serving.pools`` splits
  the fleet. Hysteresis reuses the rebalance overshoot-guard discipline:
  a dead band between thresholds, sustained evaluations, a cooldown, and
  a capacity guard on drain-down — so scale decisions are deterministic
  under the virtual clock and provably never ping-pong.
- :class:`DegradedModeController` — the ordered degradation ladder
  (``serving.degraded``): shed batch tenants first, then cap
  ``max_new_tokens``, then drop speculation, before any interactive shed;
  entry/exit hysteresis; ``Serving/degraded_level`` events.

Everything here is pure host policy over the discrete-event fleet — every
behavior is assertable deterministically under VirtualClocks, no chips.
"""

from ..telemetry.digest import LatencyDigest
from .request import CLASS_BATCH, CLASS_INTERACTIVE

# the degraded ladder, in escalation order; index == level
DEGRADED_LADDER = ("healthy", "shed_batch", "cap_tokens", "no_speculation",
                   "shed_interactive")


class BurnSensor:
    """Windowed SLO burn over a stream of digest states.

    ``update(targets_ms, digests)`` returns the worst per-metric burn rate
    over the samples added SINCE the previous call: (fraction of new
    samples whose bucket sits strictly above the target's bucket) / 0.01.
    Bucket-granular like ``evaluate_slo`` — deterministic, merge-stable.
    A window with no new samples reads 0.0 (no evidence of burn — the
    idle-fleet signal a drain-down needs). ``reset_window()`` digest swaps
    shrink the counts; such windows also read 0.0 and re-baseline.
    """

    def __init__(self):
        self._last = {}   # metric -> (count, count_above_target)

    def update(self, targets_ms, digests):
        worst = 0.0
        for key, target in (targets_ms or {}).items():
            if not key.endswith("_p99_ms") or not target or target <= 0:
                continue
            metric = key[:-len("_p99_ms")]
            d = digests.get(metric)
            if d is None:
                continue
            count = d.count
            over = d.count_above(float(target) / 1e3)
            last_count, last_over = self._last.get(metric, (0, 0))
            self._last[metric] = (count, over)
            d_count = count - last_count
            d_over = over - last_over
            if d_count > 0 and d_over > 0:
                worst = max(worst, (d_over / d_count) / 0.01)
        return worst


def _merged_digests(metrics_list):
    """Exact-merge the latency digests of N ServingMetrics (same bucket
    arithmetic the fleet rollup uses — merge order cannot matter)."""
    merged = {}
    for m in metrics_list:
        for name, d in m.latency_digests().items():
            if name not in merged:
                merged[name] = LatencyDigest()
            merged[name].merge(d)
    return merged


class Autoscaler:
    """Drain/rejoin actuation on windowed burn + queue depth.

    The Router constructs one of these when ``serving.autoscaler.enabled``
    and calls :meth:`maybe_scale` from its loop (step() and serve()),
    mirroring ``_maybe_rebalance``'s cadence. Replica GROUPS scale
    independently: the whole fleet when mixed, each prefill/decode pool
    under ``serving.pools`` (load-responsive pool sizing). Within a group:

    - **scale up** when the windowed burn rate >= ``scale_up_burn`` (or
      mean queue depth per active replica >= ``scale_up_queue_depth``)
      for ``sustain_evals`` consecutive evaluations and a standby replica
      exists: ``rejoin`` the lowest-index standby, then pull the tail of
      the deepest queue over to it (queued requests were routed before
      the capacity existed — without the pull, scale-up only helps
      arrivals that haven't happened yet);
    - **drain down** when the group is idle — burn <= ``scale_down_burn``
      AND every queue empty — for ``sustain_evals`` consecutive
      evaluations, the group sits above ``min_replicas``, and the
      CAPACITY GUARD holds: the surviving replicas' free slots can absorb
      every in-flight stream of the drained one. ``drain(migrate=True)``
      live-migrates any stragglers; the replica parks as a standby.

    No-thrash argument (the rebalance overshoot-guard discipline): the
    down threshold sits strictly below the up threshold (config-validated
    dead band), both require sustained evidence, every action starts a
    cooldown, and a down only fires when the load present at decision
    time provably fits the survivors — so the action cannot manufacture
    the opposite signal from existing load; only NEW offered load can
    re-arm it, which is a scale-up the fleet genuinely needs.
    """

    def __init__(self, router, cfg):
        self._router = router
        self.cfg = cfg
        self._calls = 0
        self._next_eval = 0.0          # cooldown gate (frontier clock)
        self._sensors = {}             # group -> BurnSensor
        self._hot = {}                 # group -> consecutive armed evals
        self._idle = {}                # group -> consecutive idle evals
        self.events = []               # scale-event timeline (snapshot)
        self._park_to_floor()

    # ------------------------------------------------------------- groups
    def _groups(self):
        """[(name, [replica indices])] — one group per pool, else the
        whole fleet. min_replicas applies per group."""
        router = self._router
        if router._pools is not None and router._pools.enabled:
            n_pre = router._pools.prefill_replicas
            idxs = list(range(len(router._replicas)))
            return [("prefill", idxs[:n_pre]), ("decode", idxs[n_pre:])]
        return [("fleet", list(range(len(router._replicas))))]

    def _active(self, idxs):
        return [i for i in idxs if not self._router._replicas[i].dead
                and not self._router._replicas[i].draining]

    def _standby(self, idxs):
        """Parked replicas a scale-up can rejoin: draining, fully drained,
        not dead (a dead replica needs a replacement engine — that is the
        failover path's business, not the autoscaler's)."""
        return [i for i in idxs
                if self._router._replicas[i].draining
                and not self._router._replicas[i].dead
                and self._router.drained(i)]

    def _park_to_floor(self):
        """Initial state: each group starts at ``min_replicas`` ACTIVE
        (lowest indices), the rest parked as standbys — the fleet the
        Router was built with is capacity, not footprint. Construction-
        time, so the drains are instant (nothing is in flight)."""
        for name, idxs in self._groups():
            for i in idxs[self.cfg.min_replicas:]:
                self._router.drain(i, migrate=True)
                self._record("park", i, name, 0.0, 0.0)

    # ------------------------------------------------------------ sensing
    def _record(self, action, idx, group, burn, queue_depth):
        self.events.append({
            "t": round(float(self._router._frontier()), 6),
            "action": action, "replica": idx, "group": group,
            "burn": round(float(burn), 4),
            "queue_depth": round(float(queue_depth), 4),
            "active": len(self._active(
                dict(self._groups())[group])),
        })

    def maybe_scale(self):
        """One control-loop evaluation (call every router loop iteration;
        self-gates on ``interval`` and ``cooldown``)."""
        self._calls += 1
        if self._calls % self.cfg.interval:
            return
        router = self._router
        now = router._frontier()
        targets = router._slo.targets_ms() if router._slo is not None else {}
        for name, idxs in self._groups():
            active = self._active(idxs)
            if not active:
                continue
            sensor = self._sensors.setdefault(name, BurnSensor())
            burn = sensor.update(
                targets,
                _merged_digests([router._replicas[i].sv.metrics
                                 for i in active]))
            depths = [router._replicas[i].sv.queue.depth for i in active]
            mean_depth = sum(depths) / len(active)
            hot = burn >= self.cfg.scale_up_burn or (
                self.cfg.scale_up_queue_depth > 0
                and mean_depth >= self.cfg.scale_up_queue_depth)
            idle = (burn <= self.cfg.scale_down_burn
                    and sum(depths) == 0)
            # the dead band between the thresholds arms NEITHER counter —
            # sustained evidence cannot straddle it
            self._hot[name] = self._hot.get(name, 0) + 1 if hot else 0
            self._idle[name] = self._idle.get(name, 0) + 1 if idle else 0
            if now < self._next_eval:
                continue  # cooling down; counters still tracked above
            if self._hot[name] >= self.cfg.sustain_evals:
                if self._scale_up(name, idxs, burn, mean_depth):
                    self._hot[name] = self._idle[name] = 0
                    self._next_eval = now + self.cfg.cooldown
            elif self._idle[name] >= self.cfg.sustain_evals \
                    and len(active) > self.cfg.min_replicas:
                if self._scale_down(name, active, burn, mean_depth):
                    self._hot[name] = self._idle[name] = 0
                    self._next_eval = now + self.cfg.cooldown

    # ----------------------------------------------------------- actuation
    def _scale_up(self, name, idxs, burn, mean_depth):
        standby = self._standby(idxs)
        if not standby:
            return False
        idx = standby[0]
        self._router.rejoin(idx)
        self._record("up", idx, name, burn, mean_depth)
        # pull the deepest backlog's tail over: those requests were routed
        # before this capacity existed, and new arrivals alone would leave
        # the standby idle while the hot queue drains token by token
        active = self._active(idxs)
        deepest = max((i for i in active if i != idx),
                      key=lambda i: self._router._replicas[i].sv.queue.depth,
                      default=None)
        if deepest is not None:
            depth = self._router._replicas[deepest].sv.queue.depth
            self._router.pull_queued(deepest, idx, depth // 2)
        return True

    def _scale_down(self, name, active, burn, mean_depth):
        idx = active[-1]   # deterministic: the highest-index active replica
        survivors = [i for i in active if i != idx]
        rep = self._router._replicas[idx].sv
        in_flight = len(rep._slots) + len(rep._prefill_jobs) \
            + rep.queue.depth
        free = sum(self._router._replicas[i].sv.n_slots
                   - len(self._router._replicas[i].sv._slots)
                   - len(self._router._replicas[i].sv._prefill_jobs)
                   for i in survivors)
        if in_flight > free:
            return False   # capacity guard: survivors must absorb the move
        self._router.drain(idx, migrate=True)
        self._record("down", idx, name, burn, mean_depth)
        return True

    # ------------------------------------------------------------ rollups
    def active_replicas(self):
        return sum(len(self._active(idxs)) for _, idxs in self._groups())

    def snapshot(self):
        return {
            "enabled": True,
            "min_replicas": self.cfg.min_replicas,
            "fleet_size": len(self._router._replicas),
            "active_replicas": self.active_replicas(),
            "groups": {name: {
                "active": self._active(idxs),
                "standby": self._standby(idxs),
            } for name, idxs in self._groups()},
            "scale_ups": sum(1 for e in self.events
                             if e["action"] == "up"),
            "scale_downs": sum(1 for e in self.events
                               if e["action"] == "down"),
            "events": list(self.events),
        }


class DegradedModeController:
    """The ordered degradation ladder (``serving.degraded``).

    One per ServingEngine; ``observe(now)`` runs on the scheduler-step
    cadence (self-gated on ``interval``) and moves at most one rung per
    evaluation, with the same sustained-evidence + dead-band hysteresis
    the autoscaler uses. Policy queries (:meth:`sheds_class`,
    :meth:`token_cap`, :meth:`speculation_off`) are read by the engine's
    submit/admission paths; level transitions emit a
    ``serving/degraded_level`` trace instant and the metrics cadence
    mirrors the level as a ``Serving/degraded_level`` scalar. Residency
    per rung is tracked for the bench artifact.
    """

    def __init__(self, cfg, slo, metrics, tracer=None, engine=None):
        self.cfg = cfg
        self.slo = slo
        self.metrics = metrics
        self.tracer = tracer
        self._engine = engine
        self.level = 0
        self._sensor = BurnSensor()
        self._steps = 0
        self._hot = 0
        self._cool = 0
        self._last_t = None
        self.residency = [0.0] * len(DEGRADED_LADDER)
        self.transitions = []   # (t, level, burn)

    def observe(self, now):
        """One scheduler step; every ``interval`` steps, one ladder
        evaluation. Returns the (possibly new) level."""
        self._steps += 1
        if self._steps % self.cfg.interval:
            return self.level
        if self._last_t is not None:
            self.residency[self.level] += max(now - self._last_t, 0.0)
        self._last_t = now
        burn = self._sensor.update(self.slo.targets_ms(),
                                   self.metrics.latency_digests())
        if burn >= self.cfg.enter_burn:
            self._hot, self._cool = self._hot + 1, 0
        elif burn <= self.cfg.exit_burn:
            self._hot, self._cool = 0, self._cool + 1
        else:
            self._hot = self._cool = 0    # dead band: no evidence either way
        new = self.level
        if self._hot >= self.cfg.enter_evals \
                and self.level < len(DEGRADED_LADDER) - 1:
            new = self.level + 1
        elif self._cool >= self.cfg.exit_evals and self.level > 0:
            new = self.level - 1
        if new != self.level:
            self._transition(new, burn, now)
        return self.level

    def _transition(self, new, burn, now):
        self.level = new
        self._hot = self._cool = 0
        self.transitions.append((round(now, 6), new, round(burn, 4)))
        if self._engine is not None and self._engine.spec:
            # rung 3 drops speculation; descending re-arms it. Safe for
            # seeded streams either way (the rng advances once per
            # dispatched step in both programs — the PR 14 pin).
            self._engine.set_speculation(not self.speculation_off())
        if self.tracer is not None:
            self.tracer.instant(
                "serving/degraded_level", cat="serving", ts=now,
                level=new, rung=DEGRADED_LADDER[new], burn=burn)

    # ------------------------------------------------------ policy queries
    def sheds_class(self, tenant_class):
        """Is this class shed at the current rung? Batch from rung 1;
        interactive ONLY at the last rung (the ladder's ordering pin)."""
        if tenant_class == CLASS_BATCH:
            return self.level >= 1
        return self.level >= len(DEGRADED_LADDER) - 1

    def token_cap(self):
        """max_new_tokens cap for new admissions (0 = uncapped)."""
        return self.cfg.max_new_tokens_cap if self.level >= 2 else 0

    def speculation_off(self):
        return self.level >= 3

    def snapshot(self):
        return {
            "level": self.level,
            "rung": DEGRADED_LADDER[self.level],
            "ladder": list(DEGRADED_LADDER),
            "residency": [round(r, 6) for r in self.residency],
            "transitions": [list(t) for t in self.transitions],
        }
