"""Scheduler clocks.

``WallClock`` is production serving; ``VirtualClock`` makes scheduling
deterministic for tests and simulation — time advances ONLY by the cost model
(`n` units per decode step, `m` per prefill token), so a unit test can assert
exact TTFT/throughput numbers and compare scheduling policies without touching
real time.
"""

import time


class WallClock:
    def now(self):
        return time.perf_counter()

    def advance(self, cost):
        """Real time advances by itself; scheduler cost hints are ignored."""

    def sleep(self, seconds):
        if seconds > 0:
            time.sleep(seconds)


class VirtualClock:
    def __init__(self, start=0.0):
        self._now = float(start)

    def now(self):
        return self._now

    def advance(self, cost):
        self._now += float(cost)

    def sleep(self, seconds):
        """Virtual sleep = jump forward (waiting for the next arrival)."""
        if seconds > 0:
            self._now += float(seconds)
