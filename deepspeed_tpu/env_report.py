"""Environment doctor.

TPU-native equivalent of the reference's ``deepspeed/env_report.py`` / ``bin/ds_report``:
prints framework, JAX/jaxlib versions, device inventory, and which optional
subsystems are importable — the "op compatibility matrix" role.
"""

import importlib
import sys


GREEN_OK = "[OKAY]"
RED_NO = "[NO]"


def _try(modname):
    try:
        importlib.import_module(modname)
        return True
    except Exception:
        return False


def main():
    import deepspeed_tpu

    print("-" * 60)
    print("DeepSpeed-TPU environment report")
    print("-" * 60)
    print(f"deepspeed_tpu ........ {deepspeed_tpu.__version__}")
    print(f"python ............... {sys.version.split()[0]}")

    try:
        import jax
        import jaxlib

        from .accelerator import get_accelerator

        accel = get_accelerator()
        print(f"jax / jaxlib ......... {jax.__version__} / {jaxlib.__version__}")
        print(f"backend .............. {jax.default_backend()}")
        print(f"accelerator .......... {accel.name} "
              f"(comm backend: {accel.communication_backend_name()})")
        print(f"devices .............. {accel.device_count()} x {accel.device_name()}")
        mem = accel.total_memory()
        if mem:
            print(f"memory/device ........ {mem / 2**30:.1f} GiB")
        print(f"process count ........ {jax.process_count()}")
        aio = accel.create_op_builder("async_io")
        if aio is not None:
            ok = aio.is_compatible()
            print(f"op async_io .......... {GREEN_OK if ok else RED_NO}")
    except Exception as e:
        print(f"jax .................. {RED_NO} ({e})")

    print("-" * 60)
    print("subsystem availability")
    print("-" * 60)
    for label, mod in [
        ("pallas (TPU kernels)", "jax.experimental.pallas"),
        ("torch (tensorboard/interop)", "torch"),
        ("transformers (HF import)", "transformers"),
        ("orbax (alt checkpointing)", "orbax.checkpoint"),
        ("einops", "einops"),
    ]:
        print(f"{label:<30} {GREEN_OK if _try(mod) else RED_NO}")
    print("-" * 60)
    return 0


if __name__ == "__main__":
    sys.exit(main())
