"""User injection policy: TP-shard a model the framework doesn't know.

Reference mode-1 injection (``deepspeed/inference/engine.py:190``
``injection_policy={TransformerLayer: ('attention.out_proj', 'mlp.down')}``):
the user names each layer's ROW-parallel output projections and DeepSpeed
splits the rest column-wise. Here sharding is logical-axes data, so the policy
maps parameter-path regexes to placements and the engine derives the specs —
no module surgery, works for any pytree model:

    deepspeed_tpu.init_inference(
        model=my_model,
        tensor_parallel={"enabled": True, "tp_size": 4},
        injection_policy={
            r"attn/(wq|wk|wv)": "column",   # output dim over the model axis
            r"attn/wo":         "row",      # input dim; XLA inserts the psum
            r"mlp/up":          "column",
            r"mlp/down":        "row",
        })

Values: ``"column"`` (last dim sharded — the Megatron ColumnParallelLinear),
``"row"`` (first dim sharded — RowParallelLinear; the SPMD partitioner places
the all-reduce the reference codes by hand in ``module_inject/layers.py``),
``"replicate"``, or an explicit logical-axes tuple like ``(None, "heads")``
(the training-side "bring-your-own-axes" vocabulary of
``parallel/sharding.py:DEFAULT_TP_RULES``).

Patterns are ``re.search``-ed against ``"/"``-joined leaf paths; the FIRST
matching pattern (insertion order) wins. A pattern matching no parameter is
an error — silent typos would serve a replicated (slow, memory-hungry) model.
"""

import re

import jax

from ..config.base import ConfigError
from ..utils.tensor_fragment import keypath_str

_COLUMN = "column"
_ROW = "row"
_REPLICATE = "replicate"


def _spec_to_axes(spec, ndim, path):
    if isinstance(spec, (tuple, list)):
        if len(spec) != ndim:
            raise ConfigError(
                f"injection_policy: axes {tuple(spec)} for {path} has "
                f"{len(spec)} entries but the parameter has {ndim} dims")
        return tuple(spec)
    if spec == _REPLICATE:
        return (None,) * ndim
    if ndim < 1:
        raise ConfigError(
            f"injection_policy: cannot {spec}-shard 0-d parameter {path}")
    if spec == _COLUMN:
        return (None,) * (ndim - 1) + ("mlp",)
    if spec == _ROW:
        return ("mlp",) + (None,) * (ndim - 1)
    raise ConfigError(
        f"injection_policy: unknown placement {spec!r} for {path} — use "
        f"'column', 'row', 'replicate', or an explicit logical-axes tuple")


def apply_injection_policy(policy, axes_tree, shapes_tree):
    """Override logical axes for every leaf whose path matches a policy
    pattern. Returns the new axes tree; raises on patterns that matched
    nothing and on shard dims the mesh math can't honor later (non-tuple
    axes)."""
    if not policy:
        return axes_tree
    compiled = [(pat, re.compile(pat), spec) for pat, spec in policy.items()]
    matched = set()

    is_axes = lambda x: isinstance(x, tuple) and all(
        isinstance(a, (str, type(None))) for a in x)
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        axes_tree, is_leaf=is_axes)
    # flatten shapes with the AXES treedef: independent is_leaf predicates
    # would desynchronize on pytrees that use tuples as containers
    shape_flat = treedef.flatten_up_to(shapes_tree)

    out = []
    for (keypath, axes), shape in zip(flat, shape_flat):
        path = keypath_str(keypath)
        for pat, rx, spec in compiled:
            if rx.search(path):
                # placement: first match wins; the typo check below still
                # credits shadowed patterns so they don't read as typos
                axes = _spec_to_axes(spec, len(shape), path)
                break
        for pat, rx, _ in compiled:
            if rx.search(path):
                matched.add(pat)
        out.append(axes)
    missing = [pat for pat, _, _ in compiled if pat not in matched]
    if missing:
        sample = [keypath_str(kp) for kp, _ in flat[:20]]
        raise ConfigError(
            f"injection_policy: pattern(s) {missing} matched no parameter — "
            f"paths look like {sample}")
    return jax.tree_util.tree_unflatten(treedef, out)
