"""Megatron-LM checkpoint import with tensor-parallel merge.

Reference role: ``runtime/state_dict_factory.py`` (``SDLoaderFactory`` /
``MegatronSDLoader``, :115-126) — loading a Megatron GPT checkpoint saved at
tensor-parallel degree N and re-partitioning it for a different degree. The
reference implements merge (src mp > target) and split (src mp < target) by
hand per weight family; here only the MERGE to the logical full tensor is
code — the re-split to ANY target topology falls out of placing the merged
tensor with a ``NamedSharding`` (``jax.device_put`` with a sharding IS the
split), same discipline as ``hf.py``.

Layout understood (Megatron-LM GPT / the reference's merge rules):

- ``<dir>/mp_rank_{XX}/model_optim_rng.pt`` or
  ``<dir>/mp_rank_{XX}_model_states.pt`` (DeepSpeed save path), each holding
  ``{'model': {'language_model': {'embedding': ..., 'transformer': ...}}}``.
- column-parallel weights (``query_key_value``, ``dense_h_to_4h``): ranks
  concatenate along the OUTPUT dim (torch axis 0); qkv additionally carries
  the per-rank head grouping handled below.
- row-parallel weights (``attention.dense``, ``dense_4h_to_h``): ranks
  concatenate along the INPUT dim (torch axis 1); their biases are
  replicated (rank 0 wins).
- ``word_embeddings``: vocab-parallel, concatenate axis 0, then trim the
  per-rank padding to the real vocab size.
- layernorms / position embeddings: replicated, rank 0 wins.

qkv layout per rank depends on ``checkpoint_version`` (reference
``merge_query_key_value``, state_dict_factory.py:205): version >= 2 stores
``[num_heads_per_rank, 3, head_dim, hidden]`` (heads-major interleave),
version 0 stores ``[3, num_heads_per_rank * head_dim, hidden]`` (qkv-major).
"""

import os
import re

import numpy as np

from ..models.transformer import CausalLM, TransformerConfig


def _rank_files(path):
    """Ordered per-TP-rank checkpoint files under ``path``."""
    out = {}
    for name in sorted(os.listdir(path)):
        m = re.fullmatch(r"mp_rank_(\d+)", name)
        if m and os.path.isdir(os.path.join(path, name)):
            for fn in ("model_optim_rng.pt", "model_states.pt"):
                f = os.path.join(path, name, fn)
                if os.path.isfile(f):
                    out[int(m.group(1))] = f
                    break
            continue
        m = re.fullmatch(r"mp_rank_(\d+)_model_states\.pt", name)
        if m:
            out[int(m.group(1))] = os.path.join(path, name)
    if not out:
        raise FileNotFoundError(
            f"no Megatron mp_rank_* checkpoints under {path}")
    ranks = sorted(out)
    if ranks != list(range(len(ranks))):
        raise ValueError(f"non-contiguous TP ranks in {path}: {ranks}")
    return [out[r] for r in ranks]


def _load_rank(f):
    import torch

    sd = torch.load(f, map_location="cpu", weights_only=False)
    # absent key means PRE-versioning (qkv-major layout) — the reference's
    # convention (state_dict_factory.py:427 get('checkpoint_version', 0));
    # defaulting to 3 would silently scramble q/k/v on old checkpoints
    version = sd.get("checkpoint_version", 0)
    model = sd.get("model", sd)
    lm = model.get("language_model", model)
    emb = lm.get("embedding", {})
    trans = lm.get("transformer", lm.get("encoder", {}))
    return {"embedding": emb, "transformer": trans, "version": version,
            "args": sd.get("args")}


def _np(t):
    import torch

    if isinstance(t, torch.Tensor):
        return t.to(torch.float32).numpy()
    return np.asarray(t, np.float32)


def _merge_qkv(parts, n_heads, head_dim, version):
    """Per-rank qkv [3*h_pp*hd, d] -> full (q, k, v) each [d_model, q_dim]
    in our [in, out] kernel layout."""
    qs, ks, vs = [], [], []
    for p in parts:
        p = _np(p)
        h_pp = p.shape[0] // (3 * head_dim)
        if version >= 2:
            # [h_pp, 3, hd, (d)] heads-major
            p = p.reshape((h_pp, 3, head_dim) + p.shape[1:])
            q, k, v = p[:, 0], p[:, 1], p[:, 2]      # [h_pp, hd, (d)]
        else:
            # [3, h_pp*hd, (d)] qkv-major
            p = p.reshape((3, h_pp * head_dim) + p.shape[1:])
            q, k, v = (x.reshape((h_pp, head_dim) + x.shape[1:]) for x in p)
        qs.append(q)
        ks.append(k)
        vs.append(v)

    def fin(chunks):
        full = np.concatenate(chunks, axis=0)          # [n_heads, hd, (d)]
        full = full.reshape((n_heads * head_dim,) + full.shape[2:])
        # torch [out, in] -> our kernel [in, out]; biases stay 1-D
        return np.ascontiguousarray(full.T) if full.ndim == 2 else full

    return fin(qs), fin(ks), fin(vs)


def load_megatron_checkpoint(path, config=None, dtype=np.float32,
                             shardings=None, **config_overrides):
    """-> (values, TransformerConfig). ``config``/overrides supply the model
    shape (a Megatron dir has no config.json; ``checkpoint['args']`` is used
    when present). ``shardings``: optional NamedSharding tree — each merged
    tensor is placed shard-wise (the reference's *split* direction)."""
    files = _rank_files(path)
    ranks = [_load_rank(f) for f in files]
    version = ranks[0]["version"]

    t0 = ranks[0]["transformer"]
    layer_ids = sorted({int(m.group(1)) for k in t0
                        for m in [re.match(r"layers\.(\d+)\.", k)] if m})
    n_layers = len(layer_ids)

    # model shape: explicit config > checkpoint args > inference from tensors
    if config is None:
        args = ranks[0]["args"]
        d_model = _np(t0["final_layernorm.weight"]).shape[0]
        if args is not None:
            cfg_kw = dict(
                vocab_size=getattr(args, "padded_vocab_size",
                                   getattr(args, "vocab_size", 0)),
                max_seq_len=getattr(args, "max_position_embeddings", 1024),
                n_layers=getattr(args, "num_layers", n_layers),
                n_heads=getattr(args, "num_attention_heads", 0),
                d_model=getattr(args, "hidden_size", d_model),
                d_ff=getattr(args, "ffn_hidden_size", 4 * d_model),
            )
        else:
            raise ValueError(
                "Megatron checkpoint has no 'args'; pass config= or "
                "config_overrides (n_heads is not inferrable from tensors)")
        cfg_kw.update(config_overrides)
        config = TransformerConfig(**cfg_kw)
    elif config_overrides:
        import dataclasses

        config = dataclasses.replace(config, **config_overrides)

    hd = config.head_dim
    tp = len(ranks)

    def cat(key, axis):
        return np.concatenate(
            [_np(r["transformer"][key]) for r in ranks], axis=axis)

    def rank0(key):
        return _np(ranks[0]["transformer"][key])

    blocks = []
    for i in layer_ids:
        p = f"layers.{i}."
        q, k, v = _merge_qkv(
            [r["transformer"][p + "attention.query_key_value.weight"]
             for r in ranks], config.n_heads, hd, version)
        qb, kb, vb = _merge_qkv(
            [r["transformer"][p + "attention.query_key_value.bias"]
             for r in ranks], config.n_heads, hd, version)
        blocks.append({
            "ln_1": {"scale": rank0(p + "input_layernorm.weight"),
                     "bias": rank0(p + "input_layernorm.bias")},
            "attn": {
                "q": {"kernel": q, "bias": qb},
                "k": {"kernel": k, "bias": kb},
                "v": {"kernel": v, "bias": vb},
                # row-parallel: in-dim split -> cat torch axis 1; bias rank 0
                "o": {"kernel": np.ascontiguousarray(
                          cat(p + "attention.dense.weight", 1).T),
                      "bias": rank0(p + "attention.dense.bias")},
            },
            "ln_2": {"scale": rank0(p + "post_attention_layernorm.weight"),
                     "bias": rank0(p + "post_attention_layernorm.bias")},
            "mlp": {
                # column-parallel: out-dim split -> cat torch axis 0
                "fc": {"kernel": np.ascontiguousarray(
                           cat(p + "mlp.dense_h_to_4h.weight", 0).T),
                       "bias": cat(p + "mlp.dense_h_to_4h.bias", 0)},
                "proj": {"kernel": np.ascontiguousarray(
                             cat(p + "mlp.dense_4h_to_h.weight", 1).T),
                         "bias": rank0(p + "mlp.dense_4h_to_h.bias")},
            },
        })

    def rank_emb(r, sub):
        node = r["embedding"][sub]
        return _np(node["weight"] if isinstance(node, dict) else node)

    wte = np.concatenate([rank_emb(r, "word_embeddings") for r in ranks],
                         axis=0)
    if wte.shape[0] < config.vocab_size:
        raise ValueError(
            f"merged vocab {wte.shape[0]} < config.vocab_size "
            f"{config.vocab_size}")
    wte = wte[:config.vocab_size]  # trim Megatron's per-rank padding

    import jax

    stacked = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs).astype(dtype), *blocks)
    values = {
        "wte": {"weight": np.asarray(wte, dtype)},
        "wpe": {"weight": np.asarray(rank_emb(ranks[0], "position_embeddings"),
                                     dtype)},
        "blocks": stacked,
        "ln_f": {"scale": np.asarray(rank0("final_layernorm.weight"), dtype),
                 "bias": np.asarray(rank0("final_layernorm.bias"), dtype)},
    }
    if shardings is not None:
        # place each merged tensor straight into its sharded layout: the
        # reference's SPLIT direction (target mp > checkpoint mp) with no
        # slicing code — device_put with a NamedSharding IS the slicing
        values = jax.tree_util.tree_map(jax.device_put, values, shardings)
    return values, config


def megatron_model_from_checkpoint(path, dtype=np.float32, config=None,
                                   **config_overrides):
    """-> (CausalLM, values) ready for init_inference(model_parameters=...)."""
    values, cfg = load_megatron_checkpoint(
        path, config=config, dtype=dtype, **config_overrides)
    return CausalLM(cfg), values
