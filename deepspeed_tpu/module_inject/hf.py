"""HuggingFace checkpoint import: weight-name mapping into the zoo's pytree.

Replaces the reference's per-architecture injection policies + checkpoint
loaders (``module_inject/containers/{gpt2,opt,bloom,llama}.py``,
``module_inject/load_checkpoint.py``, ``runtime/state_dict_factory.py:21``
Megatron merge/split): instead of walking a live torch module and swapping
containers, the checkpoint's tensor names are mapped straight into the zoo's
``CausalLM`` parameter tree. TP/ZeRO placement then falls out of the logical-axis
sharding specs — there is no per-rank slicing code because ``jax.device_put``
with a ``NamedSharding`` IS the slicing.

Memory discipline: tensors are read one at a time from safetensors / torch
pickles, stacked layer-major into the scan layout, and can be placed shard-wise
(``shardings`` arg) so the full model never needs to exist unsharded on device.

Families covered (reference containers for parity and beyond): gpt2, opt,
bloom, llama (+ mistral/qwen2 via llama-shaped paths), gpt-j, gpt-neo(x),
falcon, bert, distilbert, clip text. Each entry documents its quirks in
place.
"""

import json
import os

import numpy as np

from ..models.transformer import CausalLM, TransformerConfig


# ---------------------------------------------------------------------------
# checkpoint readers
# ---------------------------------------------------------------------------
class _Reader:
    """Tensor-by-tensor reader over safetensors (single or index-sharded) or
    torch .bin checkpoints; never holds more than one tensor at a time (plus
    torch's lazy pickle map for .bin)."""

    def __init__(self, path):
        self.path = path
        st = os.path.join(path, "model.safetensors")
        st_index = os.path.join(path, "model.safetensors.index.json")
        bin_ = os.path.join(path, "pytorch_model.bin")
        bin_index = os.path.join(path, "pytorch_model.bin.index.json")
        self._torch_maps = None
        if os.path.exists(st_index):
            index = json.load(open(st_index))["weight_map"]
            self._files = {os.path.join(path, f) for f in index.values()}
            self._where = {k: os.path.join(path, v) for k, v in index.items()}
            self._mode = "safetensors"
        elif os.path.exists(st):
            self._files = {st}
            self._where = None
            self._mode = "safetensors"
        elif os.path.exists(bin_index):
            index = json.load(open(bin_index))["weight_map"]
            self._where = {k: os.path.join(path, v) for k, v in index.items()}
            self._files = set(self._where.values())
            self._mode = "torch"
        elif os.path.exists(bin_):
            self._files = {bin_}
            self._where = None
            self._mode = "torch"
        else:
            raise FileNotFoundError(
                f"No model.safetensors[.index.json] or pytorch_model.bin under {path}")
        self._handles = {}
        self._name_set = None

    def _names_of(self, f):
        if self._mode == "safetensors":
            from safetensors import safe_open

            if f not in self._handles:
                self._handles[f] = safe_open(f, framework="pt")
            return list(self._handles[f].keys())
        return list(self._load_torch(f).keys())

    def _load_torch(self, f):
        # keep ONE file's pickle map alive (shard files are read layer-major,
        # so LRU-1 avoids holding the whole checkpoint in host RAM)
        if self._torch_maps is None or f not in self._torch_maps:
            import torch

            self._torch_maps = {f: torch.load(f, map_location="cpu",
                                              weights_only=True)}
        return self._torch_maps[f]

    def names(self):
        if self._name_set is None:
            if self._where is not None:
                out = list(self._where.keys())
            else:
                out = []
                for f in self._files:
                    out.extend(self._names_of(f))
            self._name_set = out
        return self._name_set

    def get(self, name):
        """-> np.ndarray float32."""
        f = self._where[name] if self._where is not None \
            else next(iter(self._files))
        if self._mode == "safetensors":
            from safetensors import safe_open

            if f not in self._handles:
                self._handles[f] = safe_open(f, framework="pt")
            t = self._handles[f].get_tensor(name)
        else:
            t = self._load_torch(f)[name]
        import torch

        return t.to(torch.float32).numpy()

    def has(self, name):
        return name in self.names()


# ---------------------------------------------------------------------------
# config detection
# ---------------------------------------------------------------------------
def detect_family(hf_config):
    mt = hf_config.get("model_type", "")
    if mt in ("gpt2", "opt", "bloom", "llama", "gptj", "gpt_neox", "bert",
              "distilbert", "gpt_neo", "falcon", "qwen2"):
        return mt
    if mt == "mistral":
        return "llama"
    if mt in ("clip", "clip_text_model"):
        return "clip_text"
    raise ValueError(f"Unsupported HF model_type '{mt}' "
                     "(supported: gpt2, opt, bloom, llama, mistral, gptj, "
                     "gpt_neox, bert, distilbert, gpt_neo, falcon, qwen2, clip)")


def config_from_hf(hf_config, **overrides):
    """HF config.json dict -> TransformerConfig."""
    fam = detect_family(hf_config)
    g = hf_config.get
    if fam == "gpt2":
        kw = dict(
            vocab_size=g("vocab_size"), max_seq_len=g("n_positions", 1024),
            n_layers=g("n_layer"), n_heads=g("n_head"), d_model=g("n_embd"),
            d_ff=g("n_inner") or 4 * g("n_embd"),
            activation="gelu_new", norm="layernorm", position_embedding="learned",
            tie_embeddings=True, use_bias=True, prenorm=True,
            layernorm_eps=g("layer_norm_epsilon", 1e-5),
        )
    elif fam == "opt":
        if g("word_embed_proj_dim", g("hidden_size")) != g("hidden_size"):
            raise ValueError("OPT word_embed_proj_dim != hidden_size "
                             "(350m-style projections) is not supported")
        kw = dict(
            vocab_size=g("vocab_size"), max_seq_len=g("max_position_embeddings", 2048),
            n_layers=g("num_hidden_layers"), n_heads=g("num_attention_heads"),
            d_model=g("hidden_size"), d_ff=g("ffn_dim"),
            activation={"relu": "relu", "gelu": "gelu"}[g("activation_function", "relu")],
            norm="layernorm", position_embedding="learned",
            tie_embeddings=g("tie_word_embeddings", True), use_bias=True,
            prenorm=g("do_layer_norm_before", True),
        )
    elif fam == "bloom":
        d = g("hidden_size") or g("n_embed")
        kw = dict(
            vocab_size=g("vocab_size"), max_seq_len=2048,
            n_layers=g("n_layer"), n_heads=g("n_head"), d_model=d, d_ff=4 * d,
            activation="gelu", norm="layernorm", position_embedding="alibi",
            tie_embeddings=True, use_bias=True, prenorm=True, embed_layernorm=True,
            layernorm_eps=g("layer_norm_epsilon", 1e-5),
        )
    elif fam == "gptj":
        # parallel attention+mlp with ONE shared layernorm; partial rotary;
        # untied head WITH bias (reference container: containers/gptj.py)
        d = g("n_embd")
        kw = dict(
            vocab_size=g("vocab_size"), max_seq_len=g("n_positions", 2048),
            n_layers=g("n_layer"), n_heads=g("n_head"), d_model=d,
            d_ff=g("n_inner") or 4 * d,
            activation="gelu_new", norm="layernorm", position_embedding="rope",
            rotary_dim=g("rotary_dim") or None, rotary_interleaved=True,
            tie_embeddings=False, head_bias=True, use_bias=False, mlp_bias=True,
            prenorm=True, parallel_attn_mlp=True,
            layernorm_eps=g("layer_norm_epsilon", 1e-5),
        )
    elif fam == "gpt_neox":
        # parallel residual with SEPARATE norms; partial rotary via rotary_pct
        # (reference container: containers/gptneox.py)
        d = g("hidden_size")
        hd = d // g("num_attention_heads")
        kw = dict(
            vocab_size=g("vocab_size"), max_seq_len=g("max_position_embeddings", 2048),
            n_layers=g("num_hidden_layers"), n_heads=g("num_attention_heads"),
            d_model=d, d_ff=g("intermediate_size"),
            # HF NeoX "gelu" is the exact erf form, not the tanh approximation
            activation={"gelu": "gelu_exact", "gelu_new": "gelu_new",
                        "gelu_fast": "gelu_new",
                        "relu": "relu"}[g("hidden_act", "gelu")],
            norm="layernorm", position_embedding="rope",
            rope_base=g("rotary_emb_base", 10000.0),
            rotary_dim=int(hd * g("rotary_pct", 1.0)) or None,
            tie_embeddings=g("tie_word_embeddings", False), use_bias=True,
            prenorm=True,
            parallel_attn_mlp=g("use_parallel_residual", True),
            parallel_norm_split=g("use_parallel_residual", True),
            layernorm_eps=g("layer_norm_eps", 1e-5),
        )
    elif fam == "bert":
        # post-norm encoder, no final LN, segment embeddings, MLM head
        # (reference container: containers/bert.py HFBertLayerPolicy)
        kw = dict(
            vocab_size=g("vocab_size"),
            max_seq_len=g("max_position_embeddings", 512),
            n_layers=g("num_hidden_layers"), n_heads=g("num_attention_heads"),
            d_model=g("hidden_size"), d_ff=g("intermediate_size"),
            activation={"gelu": "gelu_exact", "gelu_new": "gelu_new",
                        "relu": "relu"}[g("hidden_act", "gelu")],
            norm="layernorm", position_embedding="learned",
            tie_embeddings=True, use_bias=True, prenorm=False, causal=False,
            embed_layernorm=True, final_layernorm=False,
            type_vocab_size=g("type_vocab_size", 2),
            layernorm_eps=g("layer_norm_eps", 1e-12),
        )
    elif fam == "qwen2":
        # llama-shaped with attention bias on q/k/v only (o and MLP unbiased)
        kw = dict(
            vocab_size=g("vocab_size"), max_seq_len=g("max_position_embeddings", 2048),
            n_layers=g("num_hidden_layers"), n_heads=g("num_attention_heads"),
            n_kv_heads=g("num_key_value_heads"), d_model=g("hidden_size"),
            d_ff=g("intermediate_size"),
            activation="swiglu", norm="rmsnorm", position_embedding="rope",
            rope_base=g("rope_theta", 10000.0),
            tie_embeddings=g("tie_word_embeddings", False),
            use_bias=True, mlp_bias=False, prenorm=True,
            layernorm_eps=g("rms_norm_eps", 1e-6),
        )
    elif fam == "falcon":
        # falcon-7b style: parallel attention with ONE shared input layernorm,
        # multi-query attention, no biases, rope
        if g("new_decoder_architecture", False):
            raise ValueError("falcon new_decoder_architecture (40b-style "
                             "grouped qkv) is not supported")
        if g("alibi", False):
            raise ValueError("falcon alibi variant not supported (rope only)")
        if not g("multi_query", True):
            # HF's non-multi-query fused qkv interleaves q/k/v PER HEAD — a
            # contiguous split would silently scramble the projections
            raise ValueError("falcon multi_query=False layout not supported")
        if not g("parallel_attn", True):
            # sequential blocks read post_attention_layernorm, which the
            # parallel-attn mapping replaces with identity weights
            raise ValueError("falcon parallel_attn=False not supported")
        if g("bias", False):
            raise ValueError("falcon bias=True checkpoints not supported "
                             "(the mapping carries no bias tensors)")
        d = g("hidden_size")
        kw = dict(
            vocab_size=g("vocab_size"), max_seq_len=2048,
            n_layers=g("num_hidden_layers"), n_heads=g("num_attention_heads"),
            n_kv_heads=1, d_model=d, d_ff=4 * d,
            activation="gelu_exact", norm="layernorm", position_embedding="rope",
            rope_base=g("rope_theta", 10000.0),
            tie_embeddings=True, use_bias=False,
            prenorm=True, parallel_attn_mlp=True,
            layernorm_eps=g("layer_norm_epsilon", 1e-5),
        )
    elif fam == "clip_text":
        # CLIP text encoder (reference container: containers/clip.py): causal
        # prenorm, quick_gelu, learned positions, final LN, headless
        tc = hf_config.get("text_config", hf_config)
        g = tc.get
        kw = dict(
            vocab_size=g("vocab_size"),
            max_seq_len=g("max_position_embeddings", 77),
            n_layers=g("num_hidden_layers"), n_heads=g("num_attention_heads"),
            d_model=g("hidden_size"), d_ff=g("intermediate_size"),
            activation={"quick_gelu": "quick_gelu", "gelu": "gelu_exact"}[
                g("hidden_act", "quick_gelu")],
            norm="layernorm", position_embedding="learned",
            tie_embeddings=True, use_bias=True, prenorm=True,
            layernorm_eps=g("layer_norm_eps", 1e-5),
        )
    elif fam == "gpt_neo":
        # GPT-2-shaped but nn.Linear weights, no qkv bias, and alternating
        # global/banded-local attention (reference container: containers/gptneo.py)
        d = g("hidden_size")
        att = g("attention_layers") or []
        kw = dict(
            vocab_size=g("vocab_size"), max_seq_len=g("max_position_embeddings", 2048),
            n_layers=g("num_layers"), n_heads=g("num_heads"), d_model=d,
            d_ff=g("intermediate_size") or 4 * d,
            activation="gelu_new", norm="layernorm", position_embedding="learned",
            tie_embeddings=True, use_bias=True, mlp_bias=True, prenorm=True,
            local_attention_window=g("window_size", 256) if "local" in att else 0,
            attention_layers=tuple(att), attn_scale=1.0,  # Neo: UNSCALED logits
            layernorm_eps=g("layer_norm_epsilon", 1e-5),
        )
    elif fam == "distilbert":
        # BERT minus token types, minus pooler, gelu, 1e-12 LN eps
        # (reference container: containers/distil_bert.py)
        kw = dict(
            vocab_size=g("vocab_size"),
            max_seq_len=g("max_position_embeddings", 512),
            n_layers=g("n_layers"), n_heads=g("n_heads"), d_model=g("dim"),
            d_ff=g("hidden_dim"),
            activation={"gelu": "gelu_exact", "relu": "relu"}[g("activation", "gelu")],
            norm="layernorm", position_embedding="learned",
            tie_embeddings=True, use_bias=True, prenorm=False, causal=False,
            embed_layernorm=True, final_layernorm=False, type_vocab_size=0,
            layernorm_eps=1e-12,
        )
    else:  # llama / mistral
        kw = dict(
            vocab_size=g("vocab_size"), max_seq_len=g("max_position_embeddings", 2048),
            n_layers=g("num_hidden_layers"), n_heads=g("num_attention_heads"),
            n_kv_heads=g("num_key_value_heads"), d_model=g("hidden_size"),
            d_ff=g("intermediate_size"),
            activation="swiglu", norm="rmsnorm", position_embedding="rope",
            rope_base=g("rope_theta", 10000.0),
            tie_embeddings=g("tie_word_embeddings", False), use_bias=False,
            prenorm=True, layernorm_eps=g("rms_norm_eps", 1e-6),
        )
    kw.update(overrides)
    return TransformerConfig(**kw)


# ---------------------------------------------------------------------------
# weight mapping (per family: one function layer -> our block dict)
# ---------------------------------------------------------------------------
def _ln(r, prefix, rms=False):
    if rms:
        return {"scale": r.get(prefix + ".weight")}
    return {"scale": r.get(prefix + ".weight"), "bias": r.get(prefix + ".bias")}


def _linear_t(r, prefix, bias=True):
    """torch nn.Linear [out, in] -> our kernel [in, out]."""
    p = {"kernel": np.ascontiguousarray(r.get(prefix + ".weight").T)}
    if bias:
        p["bias"] = r.get(prefix + ".bias")
    return p


def _gpt2_block(r, cfg, i):
    # HF GPT-2 uses Conv1D: weights already [in, out]; c_attn fuses qkv along
    # the output dim (reference container: containers/gpt2.py HFGPT2LayerPolicy)
    p = f"transformer.h.{i}" if r.has(f"transformer.h.{i}.ln_1.weight") else f"h.{i}"
    w = r.get(f"{p}.attn.c_attn.weight")  # [d, 3d]
    b = r.get(f"{p}.attn.c_attn.bias")
    d = cfg.d_model
    q, k, v = w[:, :d], w[:, d:2 * d], w[:, 2 * d:]
    qb, kb, vb = b[:d], b[d:2 * d], b[2 * d:]
    return {
        "ln_1": _ln(r, f"{p}.ln_1"),
        "attn": {
            "q": {"kernel": q, "bias": qb},
            "k": {"kernel": k, "bias": kb},
            "v": {"kernel": v, "bias": vb},
            "o": {"kernel": r.get(f"{p}.attn.c_proj.weight"),
                  "bias": r.get(f"{p}.attn.c_proj.bias")},
        },
        "ln_2": _ln(r, f"{p}.ln_2"),
        "mlp": {
            "fc": {"kernel": r.get(f"{p}.mlp.c_fc.weight"),
                   "bias": r.get(f"{p}.mlp.c_fc.bias")},
            "proj": {"kernel": r.get(f"{p}.mlp.c_proj.weight"),
                     "bias": r.get(f"{p}.mlp.c_proj.bias")},
        },
    }


def _opt_block(r, cfg, i):
    p = f"model.decoder.layers.{i}" if r.has(
        f"model.decoder.layers.{i}.self_attn.q_proj.weight") \
        else f"decoder.layers.{i}"
    return {
        "ln_1": _ln(r, f"{p}.self_attn_layer_norm"),
        "attn": {
            "q": _linear_t(r, f"{p}.self_attn.q_proj"),
            "k": _linear_t(r, f"{p}.self_attn.k_proj"),
            "v": _linear_t(r, f"{p}.self_attn.v_proj"),
            "o": _linear_t(r, f"{p}.self_attn.out_proj"),
        },
        "ln_2": _ln(r, f"{p}.final_layer_norm"),
        "mlp": {
            "fc": _linear_t(r, f"{p}.fc1"),
            "proj": _linear_t(r, f"{p}.fc2"),
        },
    }


def _bloom_block(r, cfg, i):
    # BLOOM fuses qkv with per-head interleaving: rows ordered
    # (head0: q k v, head1: q k v, ...) — de-interleave before splitting
    # (reference handles this in containers/bloom.py)
    p = f"transformer.h.{i}" if r.has(
        f"transformer.h.{i}.input_layernorm.weight") else f"h.{i}"
    d, h = cfg.d_model, cfg.n_heads
    hd = d // h
    w = r.get(f"{p}.self_attention.query_key_value.weight")  # [3d, d] (out,in)
    b = r.get(f"{p}.self_attention.query_key_value.bias")
    w = w.reshape(h, 3, hd, d)
    b = b.reshape(h, 3, hd)
    mk = lambda j: {"kernel": np.ascontiguousarray(w[:, j].reshape(d, d).T),
                    "bias": b[:, j].reshape(d)}
    return {
        "ln_1": _ln(r, f"{p}.input_layernorm"),
        "attn": {
            "q": mk(0), "k": mk(1), "v": mk(2),
            "o": _linear_t(r, f"{p}.self_attention.dense"),
        },
        "ln_2": _ln(r, f"{p}.post_attention_layernorm"),
        "mlp": {
            "fc": _linear_t(r, f"{p}.mlp.dense_h_to_4h"),
            "proj": _linear_t(r, f"{p}.mlp.dense_4h_to_h"),
        },
    }


def _llama_block(r, cfg, i):
    p = f"model.layers.{i}"
    return {
        "ln_1": _ln(r, f"{p}.input_layernorm", rms=True),
        "attn": {
            "q": _linear_t(r, f"{p}.self_attn.q_proj", bias=False),
            "k": _linear_t(r, f"{p}.self_attn.k_proj", bias=False),
            "v": _linear_t(r, f"{p}.self_attn.v_proj", bias=False),
            "o": _linear_t(r, f"{p}.self_attn.o_proj", bias=False),
        },
        "ln_2": _ln(r, f"{p}.post_attention_layernorm", rms=True),
        "mlp": {
            "gate": _linear_t(r, f"{p}.mlp.gate_proj", bias=False),
            "up": _linear_t(r, f"{p}.mlp.up_proj", bias=False),
            "down": _linear_t(r, f"{p}.mlp.down_proj", bias=False),
        },
    }


def _identity_ln(d):
    return {"scale": np.ones((d,), np.float32),
            "bias": np.zeros((d,), np.float32)}


def _qwen2_block(r, cfg, i):
    """llama layout but q/k/v carry biases while o and the MLP do not —
    use_bias=True means the o slot needs a zero bias."""
    p = f"model.layers.{i}"
    o = _linear_t(r, f"{p}.self_attn.o_proj", bias=False)
    o["bias"] = np.zeros((cfg.d_model,), np.float32)
    return {
        "ln_1": _ln(r, f"{p}.input_layernorm", rms=True),
        "attn": {
            "q": _linear_t(r, f"{p}.self_attn.q_proj"),
            "k": _linear_t(r, f"{p}.self_attn.k_proj"),
            "v": _linear_t(r, f"{p}.self_attn.v_proj"),
            "o": o,
        },
        "ln_2": _ln(r, f"{p}.post_attention_layernorm", rms=True),
        "mlp": {
            "gate": _linear_t(r, f"{p}.mlp.gate_proj", bias=False),
            "up": _linear_t(r, f"{p}.mlp.up_proj", bias=False),
            "down": _linear_t(r, f"{p}.mlp.down_proj", bias=False),
        },
    }


def _falcon_block(r, cfg, i):
    """falcon-7b style: fused query_key_value [(h + 2) * hd, d] splits into
    q [d] + k [hd] + v [hd] (multi-query), ONE shared input layernorm feeding
    the parallel attn+mlp (our parallel_norm_split=False reads ln_1 only —
    ln_2 gets identity weights)."""
    p = f"transformer.h.{i}"
    w = np.ascontiguousarray(
        r.get(f"{p}.self_attention.query_key_value.weight").T)  # [d, (h+2)hd]
    d = cfg.d_model
    kv = cfg.kv_heads * cfg.head_dim
    q_w = cfg.n_heads * cfg.head_dim
    return {
        "ln_1": _ln(r, f"{p}.input_layernorm"),
        "attn": {
            "q": {"kernel": w[:, :q_w]},
            "k": {"kernel": w[:, q_w:q_w + kv]},
            "v": {"kernel": w[:, q_w + kv:]},
            "o": {"kernel": np.ascontiguousarray(
                r.get(f"{p}.self_attention.dense.weight").T)},
        },
        "ln_2": _identity_ln(d),
        "mlp": {
            "fc": {"kernel": np.ascontiguousarray(
                r.get(f"{p}.mlp.dense_h_to_4h.weight").T)},
            "proj": {"kernel": np.ascontiguousarray(
                r.get(f"{p}.mlp.dense_4h_to_h.weight").T)},
        },
    }


def _gptj_block(r, cfg, i):
    # parallel block with one shared LN: our tree still carries ln_2 (unused in
    # the shared-LN parallel path) — fill it with the identity
    p = f"transformer.h.{i}"
    return {
        "ln_1": _ln(r, f"{p}.ln_1"),
        "attn": {
            "q": _linear_t(r, f"{p}.attn.q_proj", bias=False),
            "k": _linear_t(r, f"{p}.attn.k_proj", bias=False),
            "v": _linear_t(r, f"{p}.attn.v_proj", bias=False),
            "o": _linear_t(r, f"{p}.attn.out_proj", bias=False),
        },
        "ln_2": _identity_ln(cfg.d_model),
        "mlp": {
            "fc": _linear_t(r, f"{p}.mlp.fc_in"),
            "proj": _linear_t(r, f"{p}.mlp.fc_out"),
        },
    }


def _neox_block(r, cfg, i):
    # NeoX fuses qkv with BLOOM-style per-head (q,k,v) row interleaving
    p = f"gpt_neox.layers.{i}"
    d, h = cfg.d_model, cfg.n_heads
    hd = d // h
    w = r.get(f"{p}.attention.query_key_value.weight").reshape(h, 3, hd, d)
    b = r.get(f"{p}.attention.query_key_value.bias").reshape(h, 3, hd)
    mk = lambda j: {"kernel": np.ascontiguousarray(w[:, j].reshape(d, d).T),
                    "bias": b[:, j].reshape(d)}
    return {
        "ln_1": _ln(r, f"{p}.input_layernorm"),
        "attn": {
            "q": mk(0), "k": mk(1), "v": mk(2),
            "o": _linear_t(r, f"{p}.attention.dense"),
        },
        "ln_2": _ln(r, f"{p}.post_attention_layernorm"),
        "mlp": {
            "fc": _linear_t(r, f"{p}.mlp.dense_h_to_4h"),
            "proj": _linear_t(r, f"{p}.mlp.dense_4h_to_h"),
        },
    }


def _bert_block(r, cfg, i):
    """HF BertLayer (reference container: containers/bert.py). Post-norm:
    our block computes ln_1(x + attn(x)) / ln_2(x + mlp(x)) — exactly the HF
    attention.output.LayerNorm / output.LayerNorm placement."""
    p = f"bert.encoder.layer.{i}" \
        if r.has(f"bert.encoder.layer.{i}.attention.self.query.weight") \
        else f"encoder.layer.{i}"
    return {
        "ln_1": _ln(r, f"{p}.attention.output.LayerNorm"),
        "attn": {
            "q": _linear_t(r, f"{p}.attention.self.query"),
            "k": _linear_t(r, f"{p}.attention.self.key"),
            "v": _linear_t(r, f"{p}.attention.self.value"),
            "o": _linear_t(r, f"{p}.attention.output.dense"),
        },
        "ln_2": _ln(r, f"{p}.output.LayerNorm"),
        "mlp": {
            "fc": _linear_t(r, f"{p}.intermediate.dense"),
            "proj": _linear_t(r, f"{p}.output.dense"),
        },
    }


def _neo_block(r, cfg, i):
    """HF GPTNeoBlock: nn.Linear weights (transpose), q/k/v have NO bias but
    out_proj does — zero-filled qkv biases keep the block uniform."""
    p = f"transformer.h.{i}" if r.has(f"transformer.h.{i}.ln_1.weight") \
        else f"h.{i}"
    z = np.zeros((cfg.d_model,), np.float32)

    def qkv(name):
        w = _linear_t(r, f"{p}.attn.attention.{name}", bias=False)
        w["bias"] = z
        return w

    return {
        "ln_1": _ln(r, f"{p}.ln_1"),
        "attn": {
            "q": qkv("q_proj"),
            "k": qkv("k_proj"),
            "v": qkv("v_proj"),
            "o": _linear_t(r, f"{p}.attn.attention.out_proj"),
        },
        "ln_2": _ln(r, f"{p}.ln_2"),
        "mlp": {
            "fc": _linear_t(r, f"{p}.mlp.c_fc"),
            "proj": _linear_t(r, f"{p}.mlp.c_proj"),
        },
    }


def _clip_text_block(r, cfg, i):
    """HF CLIPEncoderLayer under text_model. prenorm: layer_norm1 -> attn,
    layer_norm2 -> mlp."""
    p = f"text_model.encoder.layers.{i}"
    return {
        "ln_1": _ln(r, f"{p}.layer_norm1"),
        "attn": {
            "q": _linear_t(r, f"{p}.self_attn.q_proj"),
            "k": _linear_t(r, f"{p}.self_attn.k_proj"),
            "v": _linear_t(r, f"{p}.self_attn.v_proj"),
            "o": _linear_t(r, f"{p}.self_attn.out_proj"),
        },
        "ln_2": _ln(r, f"{p}.layer_norm2"),
        "mlp": {
            "fc": _linear_t(r, f"{p}.mlp.fc1"),
            "proj": _linear_t(r, f"{p}.mlp.fc2"),
        },
    }


def _distilbert_block(r, cfg, i):
    """HF TransformerBlock (distilbert.transformer.layer.N): post-norm like
    BERT with sa_layer_norm / output_layer_norm placement."""
    p = f"distilbert.transformer.layer.{i}" \
        if r.has(f"distilbert.transformer.layer.{i}.attention.q_lin.weight") \
        else f"transformer.layer.{i}"
    return {
        "ln_1": _ln(r, f"{p}.sa_layer_norm"),
        "attn": {
            "q": _linear_t(r, f"{p}.attention.q_lin"),
            "k": _linear_t(r, f"{p}.attention.k_lin"),
            "v": _linear_t(r, f"{p}.attention.v_lin"),
            "o": _linear_t(r, f"{p}.attention.out_lin"),
        },
        "ln_2": _ln(r, f"{p}.output_layer_norm"),
        "mlp": {
            "fc": _linear_t(r, f"{p}.ffn.lin1"),
            "proj": _linear_t(r, f"{p}.ffn.lin2"),
        },
    }


_BLOCK_FNS = {"gpt2": _gpt2_block, "opt": _opt_block, "bloom": _bloom_block,
              "bert": _bert_block, "distilbert": _distilbert_block,
              "gpt_neo": _neo_block, "clip_text": _clip_text_block,
              "qwen2": _qwen2_block, "falcon": _falcon_block,
              "llama": _llama_block, "gptj": _gptj_block,
              "gpt_neox": _neox_block}


def _first(r, *names):
    for n in names:
        if r.has(n):
            return r.get(n)
    raise KeyError(f"None of {names} in checkpoint (have e.g. {r.names()[:8]})")


def _top_level(r, cfg, fam):
    params = {}
    if fam in ("gpt2", "gpt_neo"):
        params["wte"] = {"weight": _first(r, "transformer.wte.weight", "wte.weight")}
        params["wpe"] = {"weight": _first(r, "transformer.wpe.weight", "wpe.weight")}
        lnf = "transformer.ln_f" if r.has("transformer.ln_f.weight") else "ln_f"
        params["ln_f"] = _ln(r, lnf)
    elif fam == "opt":
        pre = "model.decoder." if r.has("model.decoder.embed_tokens.weight") \
            else "decoder."
        params["wte"] = {"weight": r.get(pre + "embed_tokens.weight")}
        # OPT's learned positions are stored with a +2 offset (rows 0/1 unused
        # padding slots; HF OPTLearnedPositionalEmbedding adds the offset)
        params["wpe"] = {"weight": r.get(pre + "embed_positions.weight")[2:]}
        params["ln_f"] = _ln(r, pre + "final_layer_norm")
    elif fam == "bloom":
        pre = "transformer." if r.has("transformer.word_embeddings.weight") else ""
        params["wte"] = {"weight": r.get(pre + "word_embeddings.weight")}
        params["ln_emb"] = _ln(r, pre + "word_embeddings_layernorm")
        params["ln_f"] = _ln(r, pre + "ln_f")
    elif fam == "gptj":
        params["wte"] = {"weight": r.get("transformer.wte.weight")}
        params["ln_f"] = _ln(r, "transformer.ln_f")
        params["lm_head"] = {
            "kernel": np.ascontiguousarray(r.get("lm_head.weight").T),
            "bias": r.get("lm_head.bias")}
    elif fam == "gpt_neox":
        params["wte"] = {"weight": r.get("gpt_neox.embed_in.weight")}
        params["ln_f"] = _ln(r, "gpt_neox.final_layer_norm")
        if not cfg.tie_embeddings:
            params["lm_head"] = {
                "kernel": np.ascontiguousarray(r.get("embed_out.weight").T)}
    elif fam == "bert":
        pre = "bert." if r.has("bert.embeddings.word_embeddings.weight") else ""
        emb = pre + "embeddings."
        params["wte"] = {"weight": r.get(emb + "word_embeddings.weight")}
        params["wpe"] = {"weight": r.get(emb + "position_embeddings.weight")}
        params["wtt"] = {"weight": r.get(emb + "token_type_embeddings.weight")}
        params["ln_emb"] = _ln(r, emb + "LayerNorm")
        # MLM head (BertForMaskedLM cls.predictions); plain BertModel
        # checkpoints lack it — zero-init the transform in that case
        if r.has("cls.predictions.transform.dense.weight"):
            params["mlm_transform"] = _linear_t(
                r, "cls.predictions.transform.dense")
            params["mlm_ln"] = _ln(r, "cls.predictions.transform.LayerNorm")
            params["mlm_bias"] = {"bias": r.get("cls.predictions.bias")}
        else:
            d, v = cfg.d_model, cfg.vocab_size
            params["mlm_transform"] = {"kernel": np.eye(d, dtype=np.float32),
                                       "bias": np.zeros(d, np.float32)}
            params["mlm_ln"] = {"scale": np.ones(d, np.float32),
                                "bias": np.zeros(d, np.float32)}
            params["mlm_bias"] = {"bias": np.zeros(v, np.float32)}
    elif fam == "clip_text":
        emb = "text_model.embeddings."
        params["wte"] = {"weight": r.get(emb + "token_embedding.weight")}
        params["wpe"] = {"weight": r.get(emb + "position_embedding.weight")}
        params["ln_f"] = _ln(r, "text_model.final_layer_norm")
    elif fam == "distilbert":
        pre = "distilbert." if r.has("distilbert.embeddings.word_embeddings.weight") \
            else ""
        emb = pre + "embeddings."
        params["wte"] = {"weight": r.get(emb + "word_embeddings.weight")}
        params["wpe"] = {"weight": r.get(emb + "position_embeddings.weight")}
        params["ln_emb"] = _ln(r, emb + "LayerNorm")
        # DistilBertForMaskedLM head: vocab_transform -> gelu -> vocab_layer_norm
        # -> vocab_projector (tied weight, own bias)
        if r.has("vocab_transform.weight"):
            params["mlm_transform"] = _linear_t(r, "vocab_transform")
            params["mlm_ln"] = _ln(r, "vocab_layer_norm")
            params["mlm_bias"] = {"bias": r.get("vocab_projector.bias")}
        else:
            d, v = cfg.d_model, cfg.vocab_size
            params["mlm_transform"] = {"kernel": np.eye(d, dtype=np.float32),
                                       "bias": np.zeros(d, np.float32)}
            params["mlm_ln"] = {"scale": np.ones(d, np.float32),
                                "bias": np.zeros(d, np.float32)}
            params["mlm_bias"] = {"bias": np.zeros(v, np.float32)}
    elif fam == "falcon":
        params["wte"] = {"weight": r.get("transformer.word_embeddings.weight")}
        params["ln_f"] = _ln(r, "transformer.ln_f")
    else:  # llama / qwen2
        params["wte"] = {"weight": r.get("model.embed_tokens.weight")}
        params["ln_f"] = _ln(r, "model.norm", rms=True)
        if not cfg.tie_embeddings:
            params["lm_head"] = {
                "kernel": np.ascontiguousarray(r.get("lm_head.weight").T)}
    return params


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
def load_hf_checkpoint(path, config=None, dtype=np.float32, shardings=None):
    """Read an HF checkpoint directory -> (TransformerConfig, params values).

    ``shardings``: optional pytree of ``NamedSharding`` matching the param tree;
    when given, each stacked leaf is placed directly into its sharded device
    layout (``jax.device_put``) so the host copy is transient per-leaf and the
    model never exists fully replicated on any device — the reference needs
    ``SDLoaderFactory`` + per-rank slicing logic for this
    (``state_dict_factory.py:115-126``).
    """
    hf_cfg = json.load(open(os.path.join(path, "config.json")))
    fam = detect_family(hf_cfg)
    if config is None:
        config = config_from_hf(hf_cfg)
    r = _Reader(path)
    block_fn = _BLOCK_FNS[fam]

    blocks = [block_fn(r, config, i) for i in range(config.n_layers)]
    import jax

    stacked = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs).astype(dtype), *blocks)
    params = _top_level(r, config, fam)
    params = jax.tree_util.tree_map(lambda a: np.asarray(a, dtype), params)
    params["blocks"] = stacked

    if shardings is not None:
        params = jax.tree_util.tree_map(jax.device_put, params, shardings)
    return config, params


def hf_model_from_pretrained(path, dtype=np.float32, **config_overrides):
    """Build ``(model, params)`` from an HF checkpoint directory — CausalLM
    for decoder families, MaskedLM for bert, TextEncoder for CLIP text."""
    from ..models.transformer import MaskedLM, TextEncoder

    hf_cfg = json.load(open(os.path.join(path, "config.json")))
    fam = detect_family(hf_cfg)
    config = config_from_hf(hf_cfg, **config_overrides)
    config, params = load_hf_checkpoint(path, config=config, dtype=dtype)
    if fam == "clip_text":
        cls = TextEncoder
    elif not config.causal:
        cls = MaskedLM
    else:
        cls = CausalLM
    return cls(config), params
