"""External-model import + auto-TP serving.

TPU-native counterpart of the reference's ``module_inject/`` (3.6k LoC:
``replace_module.py:276`` walks a torch module tree and surgically swaps HF
blocks for fused containers, slicing weights per TP rank). Here the same
capability is data, not surgery: an HF checkpoint is *mapped* into the zoo's
parameter pytree (``hf.py``), and TP placement falls out of the logical-axis
sharding specs — the ``ReplaceWithTensorSlicing`` machinery disappears.
"""

from .hf import (  # noqa: F401
    config_from_hf,
    detect_family,
    load_hf_checkpoint,
    hf_model_from_pretrained,
)
from .megatron import (  # noqa: F401
    load_megatron_checkpoint,
    megatron_model_from_checkpoint,
)
from .policy import apply_injection_policy  # noqa: F401
