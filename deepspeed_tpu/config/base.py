"""Typed config models.

TPU-native equivalent of the reference's ``runtime/config_utils.py:16``
(``DeepSpeedConfigModel`` — a pydantic BaseModel with deprecated-field machinery at ``:59``).
We implement the same surface with plain dataclass-style annotations to avoid a hard
pydantic dependency: typed fields with defaults, nested models, ``new_param`` deprecation
redirects, and unknown-key warnings.
"""

import dataclasses
import enum
import typing

from ..utils.logging import logger


class ConfigError(Exception):
    pass


_MISSING = object()


def _coerce(value, annot, field_name):
    """Coerce ``value`` to the annotated type, recursing into nested ConfigModels."""
    origin = typing.get_origin(annot)
    if annot is typing.Any or value is None:
        return value
    if origin is typing.Union:  # includes Optional
        args = [a for a in typing.get_args(annot) if a is not type(None)]
        if value is None:
            return None
        last_err = None
        for a in args:
            try:
                return _coerce(value, a, field_name)
            except (TypeError, ValueError, ConfigError) as e:
                last_err = e
        raise ConfigError(f"{field_name}: cannot coerce {value!r} to {annot}: {last_err}")
    if origin in (list, tuple):
        args = typing.get_args(annot)
        elem = args[0] if args else typing.Any
        seq = [_coerce(v, elem, field_name) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        return dict(value)
    if isinstance(annot, type) and issubclass(annot, ConfigModel):
        if isinstance(value, annot):
            return value
        if isinstance(value, dict):
            return annot.from_dict(value)
        raise ConfigError(f"{field_name}: expected dict for {annot.__name__}, got {type(value)}")
    if isinstance(annot, type) and issubclass(annot, enum.Enum):
        if isinstance(value, annot):
            return value
        return annot(value)
    if annot is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            low = value.lower()
            if low in ("true", "1", "yes"):
                return True
            if low in ("false", "0", "no"):
                return False
        raise ConfigError(f"{field_name}: expected bool, got {value!r}")
    if annot is int:
        if isinstance(value, bool):
            raise ConfigError(f"{field_name}: expected int, got bool")
        if isinstance(value, int):
            return value
        if isinstance(value, float) and value.is_integer():
            return int(value)
        if isinstance(value, str):
            return int(value)
        raise ConfigError(f"{field_name}: expected int, got {value!r}")
    if annot is float:
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            return float(value)
        if isinstance(value, str):
            return float(value)
        raise ConfigError(f"{field_name}: expected float, got {value!r}")
    if annot is str:
        if isinstance(value, str):
            return value
        raise ConfigError(f"{field_name}: expected str, got {value!r}")
    return value


class ConfigModel:
    """Base for typed config sections.

    Subclasses declare fields via class annotations with defaults::

        class FP16Config(ConfigModel):
            enabled: bool = False
            loss_scale: float = 0.0

    ``deprecated_fields`` maps old key -> new key (the reference's ``new_param``
    machinery, ``runtime/config_utils.py:59``).
    """

    deprecated_fields: typing.ClassVar[dict] = {}

    def __init__(self, **kwargs):
        hints = typing.get_type_hints(type(self))
        hints = {k: v for k, v in hints.items() if not k.startswith("_") and k != "deprecated_fields"}
        for name, annot in hints.items():
            default = getattr(type(self), name, _MISSING)
            if name in kwargs:
                value = _coerce(kwargs.pop(name), annot, f"{type(self).__name__}.{name}")
            elif default is _MISSING:
                raise ConfigError(f"{type(self).__name__}: missing required field '{name}'")
            else:
                value = default() if isinstance(default, type) and issubclass(default, ConfigModel) else default
                if isinstance(value, (list, dict)):
                    value = type(value)(value)  # avoid shared mutable defaults
            setattr(self, name, value)
        if kwargs:
            raise ConfigError(f"{type(self).__name__}: unexpected fields {sorted(kwargs)}")
        self._validate()

    def _validate(self):
        """Subclass hook for cross-field validation."""

    @classmethod
    def from_dict(cls, d):
        d = dict(d or {})
        for old, new in cls.deprecated_fields.items():
            if old in d:
                logger.warning(f"Config field '{old}' is deprecated; use '{new}'")
                d.setdefault(new, d.pop(old))
        hints = typing.get_type_hints(cls)
        known = {k for k in hints if not k.startswith("_") and k != "deprecated_fields"}
        unknown = set(d) - known
        for k in sorted(unknown):
            logger.warning(f"{cls.__name__}: ignoring unknown config key '{k}'")
            d.pop(k)
        return cls(**d)

    def to_dict(self):
        out = {}
        hints = typing.get_type_hints(type(self))
        for name in hints:
            if name.startswith("_") or name == "deprecated_fields":
                continue
            value = getattr(self, name)
            if isinstance(value, ConfigModel):
                value = value.to_dict()
            elif isinstance(value, enum.Enum):
                value = value.value
            elif isinstance(value, tuple):
                value = list(value)
            out[name] = value
        return out

    def replace(self, **updates):
        d = self.to_dict()
        d.update(updates)
        return type(self).from_dict(d)

    def __repr__(self):
        fields = ", ".join(f"{k}={v!r}" for k, v in self.to_dict().items())
        return f"{type(self).__name__}({fields})"

    def __eq__(self, other):
        return type(self) is type(other) and self.to_dict() == other.to_dict()
