from .base import ConfigModel, ConfigError
from .config import (
    DeepSpeedConfig,
    OptimizerConfig,
    SchedulerConfig,
    FP16Config,
    BF16Config,
    ZeroConfig,
    MeshConfig,
    OffloadDeviceEnum,
    ActivationCheckpointingConfig,
    CommsLoggerConfig,
    FlopsProfilerConfig,
    ServingConfig,
    load_config,
)

__all__ = [
    "ConfigModel",
    "ConfigError",
    "DeepSpeedConfig",
    "OptimizerConfig",
    "SchedulerConfig",
    "FP16Config",
    "BF16Config",
    "ZeroConfig",
    "MeshConfig",
    "OffloadDeviceEnum",
    "ActivationCheckpointingConfig",
    "CommsLoggerConfig",
    "FlopsProfilerConfig",
    "ServingConfig",
    "load_config",
]
