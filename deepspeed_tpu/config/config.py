"""The single JSON config.

TPU-native equivalent of the reference's ``runtime/config.py:674`` (``DeepSpeedConfig``):
one JSON file/dict configures the whole engine. Key names mirror the reference so that
existing DeepSpeed configs port ~1:1; TPU-specific extensions (the ``mesh`` section) are
additive. The batch-size triangle (``train_batch_size = micro_batch * grad_accum *
dp_world``) is resolved and validated exactly as the reference does.
"""

import enum
import json
import os
import typing

from .base import ConfigModel, ConfigError
from ..utils.logging import logger


class OffloadDeviceEnum(str, enum.Enum):
    """Reference: ``runtime/zero/offload_config.py:12``."""

    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class OptimizerConfig(ConfigModel):
    type: str = "adamw"
    params: dict = {}


class SchedulerConfig(ConfigModel):
    type: str = ""
    params: dict = {}


class FP16Config(ConfigModel):
    """Reference: ``runtime/config.py`` fp16 section + ``runtime/fp16/loss_scaler.py``."""

    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0


class BF16Config(ConfigModel):
    enabled: bool = False


class DeepSpeedZeroOffloadParamConfig(ConfigModel):
    """Reference: ``runtime/zero/offload_config.py`` (param offload)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: str = ""
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(ConfigModel):
    """Reference: ``runtime/zero/offload_config.py`` (optimizer offload)."""

    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: str = ""
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False


class ZeroConfig(ConfigModel):
    """Reference: ``runtime/zero/config.py:76`` (``DeepSpeedZeroConfig``).

    On TPU, stages 1-3 are realized as sharding specs over the data-parallel mesh axis
    (opt state / gradients / parameters sharded respectively); XLA's SPMD partitioner
    places the reduce-scatter/allgather collectives the reference issues by hand. Bucket
    and prefetch knobs are accepted for config compatibility; the XLA scheduler makes
    most of them advisory.
    """

    stage: int = 0
    # "compiler": trust XLA's SPMD scheduling of the stage-3 param gathers;
    # "per_layer": force a gather per scanned block inside the layer loop
    # (explicit schedule — the fetch-coordinator role, bounded live params)
    zero3_gather_mode: str = "compiler"
    # How per_layer realizes the gather: "constraint" leaves the collective
    # to the partitioner (which gathers the fp32 master and converts after —
    # a measured 2x on gather wire, PARITY.md known gaps); "shard_map" emits
    # an explicit bf16 all_gather island after the compute-dtype cast, half
    # the bytes on the wire.
    zero3_gather_impl: str = "constraint"
    # Wire dtype of the per-layer weight gathers. "auto" keeps the impl's
    # historical behavior (fp32 masters under "constraint", the compute dtype
    # under "shard_map"); "fp32" gathers masters; "bf16" casts to the 16-bit
    # compute dtype before the wire (half the gather bytes); "int8" is the
    # ZeRO++-style (qwZ) blockwise-quantized gather (~quarter the bytes,
    # per-block fp32 scales). bf16/int8 require stage 3 +
    # zero3_gather_mode="per_layer" and imply the shard_map impl (a
    # constraint chain cannot pin the wire dtype — PERF.md "known 2x").
    # Masters stay sharded fp32 in every mode; only the wire payload changes.
    zero3_gather_dtype: str = "auto"
    # int8 gather quantization granularity: elements per fp32 scale block
    # (wire overhead ~ 4/block bytes/param; leaves whose last dim the block
    # does not divide fall back to one scale per row)
    zero3_gather_block: int = 256
    # Wire dtype of the gradient reduction (reduce-scatter at stage >= 2,
    # all-reduce below): "bf16" casts each micro-batch's grads before the
    # sharding constraint, halving reduce wire bytes; accumulation across
    # micro-batches then also runs in bf16 (the reference's
    # communication_data_type / grad_accum_dtype semantics). The optimizer
    # step always runs fp32 on the sharded masters.
    grad_reduce_dtype: str = "fp32"
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    round_robin_gradients: bool = False
    offload_param: DeepSpeedZeroOffloadParamConfig = DeepSpeedZeroOffloadParamConfig
    offload_optimizer: DeepSpeedZeroOffloadOptimizerConfig = DeepSpeedZeroOffloadOptimizerConfig
    sub_group_size: int = 1_000_000_000
    prefetch_bucket_size: int = 50_000_000
    param_persistence_threshold: int = 100_000
    model_persistence_threshold: int = 2 ** 62
    max_live_parameters: int = 1_000_000_000
    max_reuse_distance: int = 1_000_000_000
    gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    elastic_checkpoint: bool = False

    deprecated_fields = {
        "stage3_gather_16bit_weights_on_model_save": "gather_16bit_weights_on_model_save",
        "stage3_max_live_parameters": "max_live_parameters",
        "stage3_max_reuse_distance": "max_reuse_distance",
        "stage3_prefetch_bucket_size": "prefetch_bucket_size",
        "stage3_param_persistence_threshold": "param_persistence_threshold",
        "cpu_offload": "offload_optimizer",
    }

    def _validate(self):
        if self.stage not in (0, 1, 2, 3):
            raise ConfigError(f"zero_optimization.stage must be in 0..3, got {self.stage}")
        if self.zero3_gather_mode not in ("compiler", "per_layer"):
            raise ConfigError(
                f"zero_optimization.zero3_gather_mode must be 'compiler' or "
                f"'per_layer', got {self.zero3_gather_mode!r}")
        if self.zero3_gather_dtype not in ("auto", "fp32", "bf16", "int8"):
            raise ConfigError(
                f"zero_optimization.zero3_gather_dtype must be one of "
                f"auto|fp32|bf16|int8, got {self.zero3_gather_dtype!r}")
        if self.zero3_gather_dtype in ("bf16", "int8"):
            if self.stage != 3:
                raise ConfigError(
                    f"zero_optimization.zero3_gather_dtype="
                    f"{self.zero3_gather_dtype!r} requires stage 3 (got stage "
                    f"{self.stage}); below stage 3 params are not partitioned "
                    f"and there is no weight gather to compress")
            if self.zero3_gather_mode != "per_layer":
                raise ConfigError(
                    f"zero_optimization.zero3_gather_dtype="
                    f"{self.zero3_gather_dtype!r} requires "
                    f"zero3_gather_mode='per_layer' (got "
                    f"{self.zero3_gather_mode!r}): under 'compiler' the "
                    f"partitioner owns the gathers and reshards the fp32 "
                    f"masters — the wire dtype cannot be pinned")
        if self.zero3_gather_block < 1:
            raise ConfigError(
                f"zero_optimization.zero3_gather_block must be >= 1, got "
                f"{self.zero3_gather_block}")
        if self.grad_reduce_dtype not in ("fp32", "bf16"):
            raise ConfigError(
                f"zero_optimization.grad_reduce_dtype must be 'fp32' or "
                f"'bf16', got {self.grad_reduce_dtype!r}")

    @classmethod
    def from_dict(cls, d):
        d = dict(d or {})
        # legacy bool cpu_offload -> offload_optimizer section
        if isinstance(d.get("cpu_offload"), bool):
            flag = d.pop("cpu_offload")
            if flag:
                d.setdefault("offload_optimizer", {"device": "cpu"})
        return super().from_dict(d)


class ActivationCheckpointingConfig(ConfigModel):
    """Reference: ``runtime/activation_checkpointing/checkpointing.py`` config keys.

    On TPU this maps to ``jax.checkpoint`` (remat) policies applied to the
    scan-over-layers; ``partition_activations`` maps to sequence/TP-sharded residuals.
    """

    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: int = 0
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class MeshConfig(ConfigModel):
    """TPU-native extension: the device mesh (no reference analogue; the reference's
    ``runtime/pipe/topology.py`` ProcessTopology axes map here).

    Axis sizes; -1 on ``data`` means "use all remaining devices". Product of all axes
    must equal the device count.
    """

    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1


class HybridEngineConfig(ConfigModel):
    """RLHF hybrid engine (reference ``runtime/hybrid_engine.py:32`` +
    ``deepspeed/__init__.py:143`` selection)."""

    enabled: bool = False
    max_out_tokens: int = 512
    # rollout prompts pad to a multiple of this so PPO batches with varying
    # prompt lengths share compiled programs (1 disables)
    prompt_bucket_size: int = 64


class CheckpointConfig(ConfigModel):
    """Checkpoint engine selection (reference ``runtime/checkpoint_engine/`` +
    ``deepspeed/checkpoint/`` universal layout). "sharded" writes per-process
    index-range-addressed shards and reshapes on load across mesh changes;
    "npz" is the legacy single-file gather-to-host engine."""

    engine: str = "sharded"  # sharded | npz
    async_save: bool = False
    # transient-I/O retry (network filesystems): total attempts per durable
    # write step, and the exponential-backoff base delay in seconds
    retries: int = 3
    retry_backoff: float = 0.05

    def _validate(self):
        if self.retries < 1:
            raise ConfigError(
                f"checkpoint.retries is the TOTAL attempts per durable write "
                f"step and must be >= 1 (1 = no retry), got {self.retries}")
        if self.retry_backoff < 0:
            raise ConfigError(
                f"checkpoint.retry_backoff must be >= 0, got "
                f"{self.retry_backoff}")


class PipelineConfig(ConfigModel):
    """Pipeline-parallel schedule selection (reference ``runtime/pipe/schedule.py``:
    ``TrainSchedule`` is 1F1B, the in-flight-bounded default; "gpipe" keeps the
    AD-through-scan path whose activation footprint grows with microbatch count)."""

    schedule: str = "1f1b"  # 1f1b | gpipe


class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(ConfigModel):
    enabled: bool = False
    group: str = ""
    team: str = ""
    project: str = "deepspeed_tpu"


class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class CommsLoggerConfig(ConfigModel):
    """Reference: ``comm/config.py`` + ``utils/comms_logging.py``."""

    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: list = []


class KVPoolConfig(ConfigModel):
    """Paged KV cache (``serving/kv_pool.py``): the slot pool's KV memory is
    a fixed-shape pool of token blocks plus a per-slot block table instead of
    one dense ``n_slots x max_len`` region. Blocks are allocated/freed at
    request granularity on the host; the decode program reads through the
    (traced) block table with gathers, so it still compiles exactly once.
    Slot count stops being capped by worst-case sequence length — requests
    reserve ``ceil((prompt + max_new - 1) / block_size)`` blocks, their real
    footprint."""

    enabled: bool = False
    # tokens per KV block; serving max_len must be a multiple of it
    block_size: int = 16
    # physical blocks in the pool, INCLUDING the reserved garbage block 0
    # (freed slots' dead decode writes land there). 0 = auto: the dense
    # pool's token capacity, n_slots * (max_len / block_size) + 1.
    n_blocks: int = 0
    # "" = the engine serving dtype; "int8" stores blocks as int8 payloads
    # with per-(token, head) fp32 scales (the ZeRO++ blockwise kernels from
    # comm/collectives.py), ~halving pool HBM at a pinned logits tolerance
    kv_dtype: str = ""
    # copy-on-write shared-prefix cache: full prompt blocks are content-
    # addressed; an identical prefix maps to the SAME physical blocks
    # (refcounted) and only the suffix is prefilled
    prefix_cache: bool = True
    # reserve-as-you-decode: admission reserves only the PROMPT's blocks and
    # decode blocks are allocated as cursors advance (admission stops paying
    # for tokens not yet generated — effective concurrency rises). On pool
    # exhaustion mid-decode the newest request is preempted back to the
    # queue (resuming bitwise-identical) instead of OOM/shed. False = the
    # PR 7 whole-footprint reservation.
    on_demand_growth: bool = False
    # decode-attention backend. "gather" (default): per-layer dense view of
    # the pool through the block table, then the unchanged dense attention.
    # "fused": the split-KV flash-decode Pallas kernel
    # (ops/pallas/paged_attention.py) walks the block table IN-KERNEL — no
    # dense view is materialized. Shape-probed at engine construction
    # (fused_decode_supported); unsupported shapes warn once and fall back
    # to "gather". Prefill/insert/speculative-verify always run the gather
    # machinery either way.
    attention_backend: str = "gather"

    def _validate(self):
        if self.block_size < 1:
            raise ConfigError(
                f"kv_pool.block_size must be >= 1, got {self.block_size}")
        if self.n_blocks < 0:
            raise ConfigError(
                f"kv_pool.n_blocks must be >= 0, got {self.n_blocks}")
        if self.kv_dtype not in ("", "int8"):
            raise ConfigError(
                f"kv_pool.kv_dtype must be '' or 'int8', got {self.kv_dtype!r}")
        if self.attention_backend not in ("gather", "fused"):
            raise ConfigError(
                f"kv_pool.attention_backend must be 'gather' or 'fused', "
                f"got {self.attention_backend!r}")


class ChunkedPrefillConfig(ConfigModel):
    """Chunked prefill (``serving/engine.py``): split a long prompt's prefill
    into fixed-token chunks interleaved with decode steps, so a single long
    arrival cannot stall the co-batched decode program — a bounded-TPOT
    guarantee instead of an unbounded prefill window. Each chunk rides the
    shared-prefix suffix-prefill machinery (one compiled program per chunk
    bucket, start position traced), so chunking changes the SCHEDULE, never
    the math: greedy streams stay bitwise-equal to ``generate()``."""

    enabled: bool = False
    # tokens per prefill chunk (bucketed by the prompt-bucket policy, so all
    # full chunks share one compiled suffix program)
    chunk_size: int = 64
    # decode steps run for the co-batched slots between consecutive chunks.
    # The virtual-clock worst inter-token gap for a running decoder is
    # chunk_bucket * prefill_cost + decode_step_cost (one chunk at most
    # lands between two decode steps); raising this knob does not shrink
    # that ceiling — it slows the long prompt's prefill in exchange for
    # more decode throughput between chunks.
    decode_steps_between_chunks: int = 1

    def _validate(self):
        if self.chunk_size < 1:
            raise ConfigError(
                f"chunked_prefill.chunk_size must be >= 1, got "
                f"{self.chunk_size}")
        if self.decode_steps_between_chunks < 1:
            raise ConfigError(
                "chunked_prefill.decode_steps_between_chunks must be >= 1, "
                f"got {self.decode_steps_between_chunks}")


class RouterConfig(ConfigModel):
    """Multi-replica router (``serving/router.py``): N ServingEngine replicas
    behind a load-aware dispatcher. Scoring extends the single-replica
    shed-with-reason admission control into cross-replica balancing: replicas
    are scored on queue depth + slot/block occupancy (from
    ``ServingMetrics``), with session and prefix affinity (the paged pool's
    SHA-256 prefix chain keys as the cross-replica currency) steering
    repeated system prompts to the replica already holding their blocks."""

    # least_loaded (default) scores replicas on load; round_robin cycles
    policy: str = "least_loaded"
    # sticky sessions: requests with the same session_id land on the same
    # replica (until it drains or saturates)
    session_affinity: bool = True
    # shared prefix index: full-prompt-block chain keys -> replica, so an
    # identical system prompt routes to the replica whose paged pool already
    # caches its blocks (suffix-only prefill there)
    prefix_affinity: bool = True
    # bound on the shared prefix index (LRU past it)
    prefix_index_cap: int = 4096
    # load-score weights (normalized queue depth / slot occupancy / paged
    # block occupancy)
    queue_weight: float = 1.0
    slot_weight: float = 1.0
    block_weight: float = 1.0
    # an affinity target whose load score exceeds the best candidate's by
    # more than this margin is overridden (counted as a rebalance)
    rebalance_margin: float = 1.0

    def _validate(self):
        if self.policy not in ("least_loaded", "round_robin"):
            raise ConfigError(
                f"router.policy must be 'least_loaded' or 'round_robin', "
                f"got {self.policy!r}")
        if self.prefix_index_cap < 1:
            raise ConfigError(
                f"router.prefix_index_cap must be >= 1, got "
                f"{self.prefix_index_cap}")
        if self.rebalance_margin < 0:
            raise ConfigError(
                f"router.rebalance_margin must be >= 0, got "
                f"{self.rebalance_margin}")


class SpeculativeConfig(ConfigModel):
    """Speculative decoding on the serving stack (``serving/speculative.py``
    + the verify program in ``models/decoding.py``): a drafter proposes up
    to ``k`` tokens per greedy slot, ONE target forward over k+1 positions
    verifies them against the paged cache, and the longest agreeing prefix
    is accepted (greedy acceptance, arXiv:2211.17192 — bitwise-checkable
    against ``generate()``). Rejected candidates roll back by cursor
    decrement; blocks left entirely past the cursor are released/scrubbed
    at block granularity. Requires ``serving.kv_pool.enabled`` (rollback
    rides the block machinery). Sampled (temperature > 0) requests never
    speculate — their per-slot rng streams advance exactly once per
    dispatched step either way, so enabling/disabling speculation cannot
    perturb a seeded stream."""

    enabled: bool = False
    # "ngram" = prompt-lookup drafting, zero extra weights: match the last
    # ``ngram`` tokens against the request's own prompt+generated history
    # and propose the continuation of the most recent earlier occurrence.
    # "model" = a small draft model sharing the mesh (separate params, its
    # own tiny dense KV cache; see ``draft_model``).
    drafter: str = "ngram"
    # max draft tokens per verify step; the verify program is shaped by k
    # (drafts pad to k), so it compiles exactly once per k
    k: int = 4
    # match length for the ngram drafter
    ngram: int = 2
    # TransformerConfig overrides for the draft model (vocab_size and
    # max_seq_len are pinned to the target's); default = a 1-layer copy of
    # the target config
    draft_model: dict = {}
    # draft-model init seed (the drafter only shapes PROPOSALS — accepted
    # output is provably the target's own greedy stream either way)
    draft_seed: int = 0
    # virtual-clock cost per PROPOSED token for the model drafter (the
    # ngram drafter is free); the verify itself costs one decode step —
    # it is one target forward, which is the whole latency play
    virtual_draft_cost_per_token: float = 0.0

    def _validate(self):
        if self.drafter not in ("ngram", "model"):
            raise ConfigError(
                f"speculative.drafter must be 'ngram' or 'model', got "
                f"{self.drafter!r}")
        if self.k < 1:
            raise ConfigError(
                f"speculative.k must be >= 1, got {self.k}")
        if self.ngram < 1:
            raise ConfigError(
                f"speculative.ngram must be >= 1, got {self.ngram}")
        if self.virtual_draft_cost_per_token < 0:
            raise ConfigError(
                f"speculative.virtual_draft_cost_per_token must be >= 0, "
                f"got {self.virtual_draft_cost_per_token}")


class SLOConfig(ConfigModel):
    """Serving latency objectives (``serving.slo``): P99 targets graded
    against the streaming latency digests (``telemetry/digest.py``) that
    ``ServingMetrics`` and the Router maintain per replica and
    fleet-aggregated. A target of 0 disables that objective. When any
    target is set, the metrics cadence emits ``Serving/ttft_p99_ms``-style
    scalars plus a structured ``slo/violation`` trace event with the
    burn rate (fraction of requests over target / the 1% error budget a
    P99 objective grants) whenever the observed P99 exceeds its target;
    ``tools/fleet_report.py --fail-on slo`` turns the same grade into an
    exit code."""

    # P99 targets in milliseconds (virtual-clock units x1e3 under a
    # VirtualClock); 0 = objective off
    ttft_p99_ms: float = 0.0
    tpot_p99_ms: float = 0.0
    queue_wait_p99_ms: float = 0.0

    def _validate(self):
        for field in ("ttft_p99_ms", "tpot_p99_ms", "queue_wait_p99_ms"):
            if getattr(self, field) < 0:
                raise ConfigError(
                    f"slo.{field} must be >= 0 (0 disables), got "
                    f"{getattr(self, field)}")

    def targets_ms(self):
        """The evaluate_slo() input dict (keys carry the _p99_ms suffix)."""
        return {"ttft_p99_ms": self.ttft_p99_ms,
                "tpot_p99_ms": self.tpot_p99_ms,
                "queue_wait_p99_ms": self.queue_wait_p99_ms}

    @property
    def armed(self):
        return any(v > 0 for v in self.targets_ms().values())


class MigrationConfig(ConfigModel):
    """Live KV migration (``serving/migration.py``): serialize a running
    request's physical state — pool blocks (raw pool-dtype bytes + int8
    scales where applicable), block-table row, cursor, per-slot rng key,
    sampling knobs, prefix chain keys — into a portable snapshot and splice
    it into a peer replica through the compiled insert path. The Router
    uses it three ways: failover after a replica kill, ``drain(idx,
    migrate=True)``, and cross-replica retry. Migrated streams are bitwise
    vs stay-put (greedy and seeded sampled)."""

    enabled: bool = True
    # capture a periodic snapshot every N committed tokens per request
    # (0 = off): bounds kill-recovery replay to tokens since last snapshot
    snapshot_interval_tokens: int = 0
    # virtual-clock cost per migrated block on the TARGET replica (models
    # the splice DMA; keeps drain-vs-wait comparisons honest)
    virtual_cost_per_block: float = 0.002

    def _validate(self):
        if self.snapshot_interval_tokens < 0:
            raise ConfigError(
                f"migration.snapshot_interval_tokens must be >= 0, got "
                f"{self.snapshot_interval_tokens}")
        if self.virtual_cost_per_block < 0:
            raise ConfigError(
                f"migration.virtual_cost_per_block must be >= 0, got "
                f"{self.virtual_cost_per_block}")


class PoolsConfig(ConfigModel):
    """Disaggregated prefill/decode fleet (``serving/router.py``): partition
    the Router's replicas into a PREFILL pool (first ``prefill_replicas``
    indices) and a DECODE pool (the rest). Prefill replicas run prompts to
    the first token, capture a FRESH live-migration snapshot (partial tail
    block included — the PR 16 zero-recompute contract) and hand the stream
    off to a decode replica through the compiled insert path; decode
    replicas only ever decode. Long prompts stop interfering with in-flight
    decode latency — disaggregation ELIMINATES the interference chunked
    prefill only amortizes (DeepSpeed-Inference, arXiv:2207.00032).
    Disabled (the default) keeps every replica mixed."""

    enabled: bool = False
    # pool sizes; together they must equal the Router's replica count
    # (checked at Router construction — the config cannot see the fleet)
    prefill_replicas: int = 1
    decode_replicas: int = 1
    # per-pool chunked-prefill chunk-size overrides (0 = inherit the shared
    # serving.chunked_prefill.chunk_size): prefill replicas typically want
    # LARGER chunks (no co-resident decodes to protect), decode replicas
    # smaller ones (they only ever prefill on failover/rebalance splices)
    prefill_chunk_size: int = 0
    decode_chunk_size: int = 0
    # per-pool speculative-decoding overrides ("" = inherit serving.
    # speculative.enabled, "on"/"off" = force): speculation only pays on
    # the decode pool — a prefill replica holds each stream for one token
    prefill_speculation: str = ""
    decode_speculation: str = ""

    def _validate(self):
        if self.prefill_replicas < 1:
            raise ConfigError(
                f"pools.prefill_replicas must be >= 1, got "
                f"{self.prefill_replicas}")
        if self.decode_replicas < 1:
            raise ConfigError(
                f"pools.decode_replicas must be >= 1, got "
                f"{self.decode_replicas}")
        for field in ("prefill_chunk_size", "decode_chunk_size"):
            if getattr(self, field) < 0:
                raise ConfigError(
                    f"pools.{field} must be >= 0 (0 inherits), got "
                    f"{getattr(self, field)}")
        for field in ("prefill_speculation", "decode_speculation"):
            if getattr(self, field) not in ("", "on", "off"):
                raise ConfigError(
                    f"pools.{field} must be '', 'on' or 'off', got "
                    f"{getattr(self, field)!r}")


class RebalanceConfig(ConfigModel):
    """Live decode rebalancing (``serving/router.py``): the actuator over
    the live-migration mechanism — the Router watches per-replica load
    scores (occupancy, queue depth, the same signals routing uses) and
    migrates long-tail decode streams off hot replicas mid-flight. The
    trigger is hysteresis-guarded so it provably never thrashes: a move
    fires only when the hot/cold load gap exceeds ``min_gain`` (and a move
    of one stream cannot invert a gap that large back past the threshold),
    at most ``max_concurrent`` streams move per trigger, and the trigger
    then cools down for ``cooldown`` seconds. Voluntary moves never burn
    the ``serving.retry_limit`` budget."""

    enabled: bool = False
    # minimum hot-minus-cold load-score gap before any stream moves; also
    # the hysteresis band — below it the fleet is "balanced enough"
    min_gain: float = 0.25
    # seconds (virtual under a VirtualClock) between triggers
    cooldown: float = 0.5
    # streams moved per trigger (bounded blast radius)
    max_concurrent: int = 1
    # router loop iterations between load evaluations (the check is cheap
    # but per-step evaluation would just hit the cooldown gate anyway)
    interval: int = 8

    def _validate(self):
        if self.min_gain <= 0:
            raise ConfigError(
                f"rebalance.min_gain must be > 0 (the hysteresis band), "
                f"got {self.min_gain}")
        if self.cooldown < 0:
            raise ConfigError(
                f"rebalance.cooldown must be >= 0, got {self.cooldown}")
        if self.max_concurrent < 1:
            raise ConfigError(
                f"rebalance.max_concurrent must be >= 1, got "
                f"{self.max_concurrent}")
        if self.interval < 1:
            raise ConfigError(
                f"rebalance.interval must be >= 1, got {self.interval}")


class AutoscalerConfig(ConfigModel):
    """SLO-driven replica autoscaling (``serving/control.py``): the Router
    watches the windowed ``slo_burn_rate`` + queue depth of each replica
    group (the whole fleet, or each prefill/decode pool independently under
    ``serving.pools``) and scales the ACTIVE replica set through the
    existing drain(migrate=True)/rejoin lifecycle — scale up on sustained
    burn, drain down on sustained idle. The fleet the Router was built
    with is the ceiling; ``min_replicas`` is the floor (per pool when
    pools are enabled). Hysteresis follows the rebalance overshoot-guard
    discipline: a dead band between the up and down thresholds, N
    consecutive evaluations before any action, a cooldown between actions,
    and a capacity guard that refuses a drain-down unless the surviving
    replicas can absorb every in-flight stream — so the controller
    provably never thrashes (a down can only fire when it cannot
    re-create the up signal from the load present at decision time)."""

    enabled: bool = False
    # floor of ACTIVE replicas (per pool under serving.pools); the replica
    # list the Router was constructed with is the ceiling
    min_replicas: int = 1
    # windowed burn rate (samples since the previous evaluation) at/above
    # which an evaluation counts toward scale-up
    scale_up_burn: float = 1.0
    # windowed burn rate at/below which (with an empty queue) an
    # evaluation counts toward drain-down; must sit strictly below
    # scale_up_burn — this gap IS the hysteresis dead band
    scale_down_burn: float = 0.25
    # mean queue depth per active replica that also arms scale-up
    # (0 disables the queue trigger; burn alone then drives it)
    scale_up_queue_depth: float = 0.0
    # consecutive armed evaluations before an action fires
    sustain_evals: int = 2
    # seconds (virtual under a VirtualClock) between scale actions
    cooldown: float = 4.0
    # router loop iterations between evaluations (cf. rebalance.interval)
    interval: int = 8

    def _validate(self):
        if self.min_replicas < 1:
            raise ConfigError(
                f"autoscaler.min_replicas must be >= 1, got "
                f"{self.min_replicas}")
        if self.scale_up_burn <= 0:
            raise ConfigError(
                f"autoscaler.scale_up_burn must be > 0, got "
                f"{self.scale_up_burn}")
        if not 0 <= self.scale_down_burn < self.scale_up_burn:
            raise ConfigError(
                "autoscaler.scale_down_burn must sit in [0, scale_up_burn) "
                f"— the hysteresis dead band — got {self.scale_down_burn} "
                f"vs scale_up_burn={self.scale_up_burn}")
        if self.scale_up_queue_depth < 0:
            raise ConfigError(
                f"autoscaler.scale_up_queue_depth must be >= 0 (0 "
                f"disables), got {self.scale_up_queue_depth}")
        if self.sustain_evals < 1:
            raise ConfigError(
                f"autoscaler.sustain_evals must be >= 1, got "
                f"{self.sustain_evals}")
        if self.cooldown < 0:
            raise ConfigError(
                f"autoscaler.cooldown must be >= 0, got {self.cooldown}")
        if self.interval < 1:
            raise ConfigError(
                f"autoscaler.interval must be >= 1, got {self.interval}")


class TenantClassConfig(ConfigModel):
    """One tenant class (``serving.tenants.interactive`` / ``.batch``):
    the weighted-fair share, the per-tenant token-bucket admission budget,
    and an optional per-class TTFT objective for per-tenant SLO grading."""

    # weighted-fair admission share (start-time fair queuing over tenants:
    # a tenant's virtual time advances by admitted_tokens / weight)
    weight: float = 1.0
    # per-TENANT token-bucket budget: sustained admitted tokens
    # (prompt + max_new_tokens) per second (virtual under a VirtualClock);
    # 0 = unlimited. Over-budget requests WAIT in the queue (deferral,
    # not shedding) until the bucket refills — enforcement is exact under
    # the virtual clock.
    token_budget_per_s: float = 0.0
    # bucket capacity (burst); 0 = one second's refill (token_budget_per_s)
    token_budget_burst: float = 0.0
    # per-class TTFT P99 target for per-tenant SLO grades (ms; 0 inherits
    # serving.slo.ttft_p99_ms)
    ttft_p99_ms: float = 0.0

    def _validate(self):
        if self.weight <= 0:
            raise ConfigError(
                f"tenants class weight must be > 0, got {self.weight}")
        for field in ("token_budget_per_s", "token_budget_burst",
                      "ttft_p99_ms"):
            if getattr(self, field) < 0:
                raise ConfigError(
                    f"tenants class {field} must be >= 0, got "
                    f"{getattr(self, field)}")


class TenantsConfig(ConfigModel):
    """Multi-tenant QoS (``serving.tenants``): requests carry a
    ``tenant_id`` + a class (``interactive`` | ``batch``); admission
    becomes weighted-fair across tenants (``serving.policy:
    "weighted_fair"``) with per-tenant token budgets, and a latency-class
    arrival may evict a batch-class stream mid-flight through the
    rollback-safe preemption machinery (the evicted stream resumes
    bitwise-identically — the PR 12/14 contract)."""

    enabled: bool = False
    interactive: TenantClassConfig = None   # default weight 4.0
    batch: TenantClassConfig = None         # default weight 1.0
    # priority preemption: when no slot is free and an arrived interactive
    # request waits, preempt the NEWEST-admitted batch-class stream
    # (paged pools only — preemption rides the block-release machinery)
    preempt: bool = True

    def _validate(self):
        if self.interactive is None:
            self.interactive = TenantClassConfig(weight=4.0)
        if self.batch is None:
            self.batch = TenantClassConfig(weight=1.0)

    def class_config(self, tenant_class):
        return self.batch if tenant_class == "batch" else self.interactive


class DegradedConfig(ConfigModel):
    """Degraded modes as first-class policy (``serving.degraded``): an
    ordered ladder the engine climbs under sustained SLO burn and descends
    when the burn clears, with entry/exit hysteresis so the ladder never
    oscillates. Rungs, in order: (1) shed new batch-class requests,
    (2) also cap ``max_new_tokens`` on new admissions, (3) also drop
    speculation (the compiled verify stays warm; seeded streams are
    unaffected — the PR 14 pin), (4) shed interactive too — the last
    resort. Interactive traffic is never shed before rung 4."""

    enabled: bool = False
    # windowed burn rate at/above which an evaluation counts toward
    # climbing one rung
    enter_burn: float = 1.0
    # windowed burn rate at/below which an evaluation counts toward
    # descending one rung; must sit strictly below enter_burn
    exit_burn: float = 0.25
    # consecutive armed evaluations before a rung change
    enter_evals: int = 2
    exit_evals: int = 2
    # rung 2+: max_new_tokens cap applied to NEW admissions
    max_new_tokens_cap: int = 8
    # scheduler steps between evaluations
    interval: int = 8

    def _validate(self):
        if self.enter_burn <= 0:
            raise ConfigError(
                f"degraded.enter_burn must be > 0, got {self.enter_burn}")
        if not 0 <= self.exit_burn < self.enter_burn:
            raise ConfigError(
                "degraded.exit_burn must sit in [0, enter_burn) — the "
                f"hysteresis dead band — got {self.exit_burn} vs "
                f"enter_burn={self.enter_burn}")
        for field in ("enter_evals", "exit_evals", "interval"):
            if getattr(self, field) < 1:
                raise ConfigError(
                    f"degraded.{field} must be >= 1, got "
                    f"{getattr(self, field)}")
        if self.max_new_tokens_cap < 1:
            raise ConfigError(
                f"degraded.max_new_tokens_cap must be >= 1, got "
                f"{self.max_new_tokens_cap}")


class ServingConfig(ConfigModel):
    """Continuous-batching serving (Orca-style slot scheduler over ONE jitted
    decode program; DeepSpeed-Inference's serving-side batching layer,
    TPU-native). Consumed by ``serving/engine.py`` via the inference config's
    ``serving`` block."""

    # fixed decode batch-slot pool: static shapes, compiled once; finished
    # requests free their slot mid-flight and queued ones are spliced in
    n_slots: int = 8
    # per-slot KV window (prompt + generation); 0 = inference max_tokens
    max_len: int = 0
    # admission control: requests beyond this queue depth are shed with a
    # reason instead of growing until OOM
    max_queue_depth: int = 64
    # prefill/decode interleaving: at most this many prefills per scheduler
    # step, so a burst of arrivals can't starve running decodes (TPOT)
    max_prefills_per_step: int = 1
    # admission policy: "fcfs" (strict arrival order + bounded HOL bypass)
    # or "weighted_fair" (start-time fair queuing across tenants with
    # per-tenant token budgets; serving.tenants configures the classes)
    policy: str = "fcfs"
    # deterministic virtual-clock mode (tests/simulation): scheduler time
    # advances by the cost model below instead of the wall clock
    virtual_clock: bool = False
    virtual_decode_step_cost: float = 1.0
    virtual_prefill_cost_per_token: float = 0.0625  # ~flash prefill vs decode
    # zero freed KV memory when a request finishes (the causal mask and
    # whole-row/whole-block insert already prevent stale-KV leaks; hygiene/
    # debug knob). Dense pool: zero the slot's rows; paged pool: zero each
    # physical block as its refcount hits zero (block-granularity scrub).
    scrub_freed_slots: bool = False
    # emit Serving/* monitor events every N scheduler steps (0 disables)
    monitor_interval: int = 32
    # paged + quantized KV cache with shared-prefix reuse (kv_pool.enabled)
    kv_pool: KVPoolConfig = None
    # chunked prefill: interleave fixed-token prefill chunks with decode
    # steps for a bounded co-batched TPOT (chunked_prefill.enabled)
    chunked_prefill: ChunkedPrefillConfig = None
    # multi-replica router policy (serving/router.py reads this block off
    # its first replica's config unless given one explicitly)
    router: RouterConfig = None
    # latency SLO targets graded against the streaming digests (per replica
    # and fleet-aggregated); 0 targets = no objective
    slo: SLOConfig = None
    # head-of-line bypass under block-aware admission: when the queue head's
    # KV footprint cannot fit, up to this many later requests that DO fit may
    # be admitted past it before admissions stop until the head clears
    # (bounded starvation). 0 = strict FCFS, nothing ever overtakes the head.
    hol_bypass_limit: int = 0
    # speculative decoding: drafter + one-forward verify + rollback-safe
    # greedy acceptance over the paged pool (speculative.enabled)
    speculative: SpeculativeConfig = None
    # live KV migration: portable request snapshots spliced between
    # replicas (failover, drain-by-migration, cross-replica retry)
    migration: MigrationConfig = None
    # disaggregated prefill/decode pools over the Router's replicas
    # (pools.enabled): prefill replicas hand streams off at first-token
    # time through the migration machinery
    pools: PoolsConfig = None
    # live decode rebalancing: hysteresis-guarded migration of long-tail
    # streams off hot replicas (rebalance.enabled)
    rebalance: RebalanceConfig = None
    # SLO-driven replica autoscaling over the Router's fleet
    # (autoscaler.enabled): drain/rejoin actuation on windowed burn rate
    autoscaler: AutoscalerConfig = None
    # tenant/priority classes: weighted-fair admission shares, per-tenant
    # token budgets, priority preemption (tenants.enabled)
    tenants: TenantsConfig = None
    # degraded-mode ladder under SLO burn: shed batch -> cap tokens ->
    # drop speculation -> shed interactive, hysteresis-guarded
    degraded: DegradedConfig = None
    # cross-replica retry budget: a request that hits a recoverable
    # per-replica failure (unhealthy_slot, replica crash) is re-dispatched
    # to a different replica up to this many times before the terminal shed
    retry_limit: int = 1

    def _validate(self):
        if self.kv_pool is None:
            self.kv_pool = KVPoolConfig()
        if self.chunked_prefill is None:
            self.chunked_prefill = ChunkedPrefillConfig()
        if self.router is None:
            self.router = RouterConfig()
        if self.slo is None:
            self.slo = SLOConfig()
        if self.speculative is None:
            self.speculative = SpeculativeConfig()
        if self.migration is None:
            self.migration = MigrationConfig()
        if self.pools is None:
            self.pools = PoolsConfig()
        if self.rebalance is None:
            self.rebalance = RebalanceConfig()
        if self.autoscaler is None:
            self.autoscaler = AutoscalerConfig()
        if self.tenants is None:
            self.tenants = TenantsConfig()
        if self.degraded is None:
            self.degraded = DegradedConfig()
        if self.pools.enabled and not self.kv_pool.enabled:
            raise ConfigError(
                "serving.pools.enabled requires serving.kv_pool.enabled: "
                "the first-token handoff splices a fresh paged-pool "
                "snapshot into the decode replica (the PR 16 zero-"
                "recompute contract has no dense-pool form)")
        if self.pools.enabled and not self.migration.enabled:
            raise ConfigError(
                "serving.pools.enabled requires serving.migration.enabled: "
                "the first-token handoff IS a live migration")
        if self.retry_limit < 0:
            raise ConfigError(
                f"serving.retry_limit must be >= 0, got {self.retry_limit}")
        if self.speculative.enabled and not self.kv_pool.enabled:
            raise ConfigError(
                "serving.speculative.enabled requires serving.kv_pool."
                "enabled: acceptance rollback (cursor decrement + stale-"
                "block release/scrub) rides the paged-pool block machinery")
        if self.hol_bypass_limit < 0:
            raise ConfigError(
                f"serving.hol_bypass_limit must be >= 0, got "
                f"{self.hol_bypass_limit}")
        if self.n_slots < 1:
            raise ConfigError(f"serving.n_slots must be >= 1, got {self.n_slots}")
        if self.max_queue_depth < 1:
            raise ConfigError(
                f"serving.max_queue_depth must be >= 1, got {self.max_queue_depth}")
        if self.policy not in ("fcfs", "weighted_fair"):
            raise ConfigError(
                f"serving.policy must be 'fcfs' or 'weighted_fair', got "
                f"{self.policy!r}")
        if self.max_prefills_per_step < 1:
            raise ConfigError("serving.max_prefills_per_step must be >= 1")
        if self.autoscaler.enabled and not self.slo.armed \
                and self.autoscaler.scale_up_queue_depth <= 0:
            raise ConfigError(
                "serving.autoscaler.enabled needs a sensor: set a "
                "serving.slo target (burn-rate trigger) and/or "
                "autoscaler.scale_up_queue_depth (queue trigger)")
        if self.degraded.enabled and not self.slo.armed:
            raise ConfigError(
                "serving.degraded.enabled requires a serving.slo target: "
                "the ladder's only input is the windowed SLO burn rate")


class TelemetryConfig(ConfigModel):
    """Span-based step tracing (``telemetry/tracer.py``): nested host spans
    over the engine's step phases (data/fwd/bwd/step/checkpoint), serving
    request lifecycles, and checkpoint save/resume, emitted as Chrome-trace
    JSON (Perfetto-loadable) + structured JSONL under
    ``<output_path>/<job_name>/``. ``device_sync`` fences span ends (and the
    wall-clock timers) with ``block_until_ready`` so timings measure device
    execution rather than dispatch."""

    enabled: bool = False
    output_path: str = ""  # trace dir root; "" -> ./traces
    job_name: str = "DeepSpeedJobName"
    # fence sync=True spans + the fwd/bwd/step timers on the device
    device_sync: bool = False
    chrome_trace: bool = True  # write trace.json (chrome://tracing/Perfetto)
    jsonl: bool = True         # write spans.jsonl (tools/trace_summary.py)
    # in-memory event cap; past it new events are dropped (and counted)
    max_events: int = 100_000

    def _validate(self):
        if self.max_events < 1:
            raise ConfigError(
                f"telemetry.max_events must be >= 1, got {self.max_events}")


class HealthConfig(ConfigModel):
    """Numerics flight recorder (``telemetry/health.py``): per-param-group
    health stats computed inside the jitted step (always traced as a small
    side output), a host-side ring buffer + anomaly watchdog (this block
    arms it), and atomically-committed black-box dumps on detector fire /
    SIGTERM / unhandled train_batch exceptions. Detector actions:
    ``off | warn | skip_step | dump | halt`` — ``skip_step`` is realized
    in-graph (the fp16 overflow-skip generalized to any-dtype non-finite
    grads) and only applies to the nonfinite detector; ``halt`` dumps and
    raises ``HealthHalted``. On the serving side, ``enabled`` arms the
    nonfinite-logit watchdog (``Serving/health_*`` events + the
    ``unhealthy_slot`` shed)."""

    enabled: bool = False
    # ring buffer length (steps kept for the black-box dump) and the
    # observe cadence (1 = every step; observing syncs the step's stats)
    window: int = 256
    check_interval: int = 1
    # write Health/* scalar events through the monitor fan-out per observe
    emit_events: bool = True
    # detector: any non-finite grad/param element, naming the param group
    nonfinite_action: str = "dump"
    # detector: z-score spike of loss / grad_norm over a trailing window
    spike_zscore: float = 6.0
    spike_window: int = 32
    spike_min_steps: int = 8
    spike_action: str = "warn"
    # detector: per-group update/param ratio ceiling (0 disables)
    update_ratio_max: float = 0.0
    update_ratio_action: str = "warn"
    # black-box dump root ("" -> ./health_dumps), dump triggers, and the
    # per-run dump cap (a flapping detector must not fill the disk)
    dump_dir: str = ""
    max_dumps: int = 8
    dump_on_exception: bool = True
    dump_on_signal: bool = True

    def _validate(self):
        from ..telemetry.health import ACTIONS

        for field in ("nonfinite_action", "spike_action",
                      "update_ratio_action"):
            v = getattr(self, field)
            if v not in ACTIONS:
                raise ConfigError(
                    f"health.{field} must be one of {'|'.join(ACTIONS)}, "
                    f"got {v!r}")
        if self.window < 8:
            raise ConfigError(
                f"health.window must be >= 8 (detectors need history), "
                f"got {self.window}")
        if self.check_interval < 1:
            raise ConfigError(
                f"health.check_interval must be >= 1, got "
                f"{self.check_interval}")
        if self.spike_window < 1 or self.spike_min_steps < 1:
            raise ConfigError(
                f"health.spike_window and health.spike_min_steps must be "
                f">= 1, got {self.spike_window}/{self.spike_min_steps}")
        if self.max_dumps < 1:
            raise ConfigError(
                f"health.max_dumps must be >= 1, got {self.max_dumps}")


class ElasticConfig(ConfigModel):
    """Preemption-native elastic training (``checkpoint/snapshot.py`` +
    ``elasticity/agent.py``). ``enabled`` arms overlapped snapshots: the
    agent keeps a double-buffered host shadow of the full step state,
    captured every ``snapshot_interval`` steps (async device-to-host issue,
    no file I/O on the step path) and drained to a published sharded tag by
    a background writer. On SIGTERM the flush commits the freshest
    already-staged shadow — bounded by one snapshot write, never a
    from-scratch save — so a preemption loses at most ``snapshot_interval``
    steps. The grace budgeter measures real write+fsync time per snapshot
    and warns (once per run) when ``flush_time * safety_factor`` no longer
    fits ``grace_period_s``, stretching the cadence within
    ``[snapshot_interval, max_interval]`` when the writer can't keep up."""

    enabled: bool = False
    # steps between shadow captures (the max steps a preemption can lose)
    snapshot_interval: int = 1
    # the preemption grace window the SIGTERM flush must fit (seconds)
    grace_period_s: float = 30.0
    # flush must fit grace_period_s / safety_factor before the budgeter warns
    safety_factor: float = 2.0
    # cadence ceiling when the budgeter stretches a too-slow writer
    max_interval: int = 64
    # keep the newest N snapshot tags (retention; None = keep everything)
    keep_last: typing.Optional[int] = 4

    def _validate(self):
        if self.snapshot_interval < 1:
            raise ConfigError(
                f"elastic.snapshot_interval must be >= 1, got "
                f"{self.snapshot_interval}")
        if self.max_interval < self.snapshot_interval:
            raise ConfigError(
                f"elastic.max_interval must be >= snapshot_interval "
                f"({self.snapshot_interval}), got {self.max_interval}")
        if self.grace_period_s <= 0:
            raise ConfigError(
                f"elastic.grace_period_s must be > 0, got "
                f"{self.grace_period_s}")
        if self.safety_factor < 1.0:
            raise ConfigError(
                f"elastic.safety_factor must be >= 1.0, got "
                f"{self.safety_factor}")
        if self.keep_last is not None and self.keep_last < 1:
            raise ConfigError(
                f"elastic.keep_last must be >= 1 or null, got "
                f"{self.keep_last}")


class FlopsProfilerConfig(ConfigModel):
    """Reference: ``profiling/config.py``."""

    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: str = ""


class DataTypesConfig(ConfigModel):
    grad_accum_dtype: typing.Optional[str] = None


class GradientCompressionConfig(ConfigModel):
    """Quantized-collective slot (reference's 1-bit Adam / compressed allreduce,
    ``runtime/comm/nccl.py:54``; cf. EQuARX for the XLA analogue)."""

    enabled: bool = False
    bits: int = 8


class CurriculumConfig(ConfigModel):
    """Curriculum learning (reference legacy top-level ``curriculum_learning``
    section, consumed by the engine at ``engine.py:1675`` for seqlen
    scheduling). Scheduler keys pass through to ``CurriculumScheduler``."""

    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: dict = {}


class ProgressiveLayerDropConfig(ConfigModel):
    """Reference ``progressive_layer_drop`` section (``engine.py:680``,
    ``runtime/progressive_layer_drop.py``): stochastic depth with the
    theta(t) = (1-theta_bar) exp(-gamma t) + theta_bar keep schedule."""

    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


class DeepSpeedConfig(ConfigModel):
    """Top-level config (reference ``runtime/config.py:674``)."""

    train_batch_size: typing.Optional[int] = None
    train_micro_batch_size_per_gpu: typing.Optional[int] = None
    gradient_accumulation_steps: typing.Optional[int] = None
    steps_per_print: int = 10
    optimizer: OptimizerConfig = OptimizerConfig
    scheduler: SchedulerConfig = SchedulerConfig
    fp16: FP16Config = FP16Config
    bf16: BF16Config = BF16Config
    zero_optimization: ZeroConfig = ZeroConfig
    zero_allow_untested_optimizer: bool = False
    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    sparse_gradients: bool = False
    activation_checkpointing: ActivationCheckpointingConfig = ActivationCheckpointingConfig
    mesh: MeshConfig = MeshConfig
    pipeline: PipelineConfig = PipelineConfig
    checkpoint: CheckpointConfig = CheckpointConfig
    hybrid_engine: HybridEngineConfig = HybridEngineConfig
    tensorboard: TensorBoardConfig = TensorBoardConfig
    wandb: WandbConfig = WandbConfig
    csv_monitor: CSVConfig = CSVConfig
    telemetry: TelemetryConfig = TelemetryConfig
    health: HealthConfig = HealthConfig
    elastic: ElasticConfig = ElasticConfig
    comms_logger: CommsLoggerConfig = CommsLoggerConfig
    flops_profiler: FlopsProfilerConfig = FlopsProfilerConfig
    data_types: DataTypesConfig = DataTypesConfig
    curriculum_learning: CurriculumConfig = CurriculumConfig
    progressive_layer_drop: ProgressiveLayerDropConfig = ProgressiveLayerDropConfig
    gradient_compression: GradientCompressionConfig = GradientCompressionConfig
    # compression-in-training (reference compression_training section,
    # deepspeed/compression/config.py): parsed by compression.init_compression
    # — kept as a raw dict here to avoid a config<->compression import cycle
    compression_training: dict = {}
    communication_data_type: typing.Optional[str] = None
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dump_state: bool = False
    gradient_checkpointing: bool = False
    seed: int = 1234

    deprecated_fields = {"train_micro_batch_size": "train_micro_batch_size_per_gpu"}

    # -- batch triangle -------------------------------------------------------------
    def resolve_batch_size(self, dp_world_size):
        """Resolve/validate the batch-size triangle against ``dp_world_size``.

        Mirrors the reference's ``DeepSpeedConfig._configure_train_batch_size``
        (``runtime/config.py``): given any subset of {train_batch_size,
        train_micro_batch_size_per_gpu, gradient_accumulation_steps}, infer the rest,
        and check ``train = micro * grad_accum * dp_world``.
        """
        tbs = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        for name, v in (("train_batch_size", tbs),
                        ("train_micro_batch_size_per_gpu", micro),
                        ("gradient_accumulation_steps", gas),
                        ("dp_world_size", dp_world_size)):
            if v is not None and v <= 0:
                raise ConfigError(f"{name} must be positive, got {v}")

        if tbs is not None and micro is not None and gas is None:
            gas, rem = divmod(tbs, micro * dp_world_size)
            if rem:
                raise ConfigError(
                    f"train_batch_size {tbs} is not divisible by "
                    f"micro_batch {micro} * dp_world {dp_world_size}"
                )
        elif tbs is not None and micro is None and gas is not None:
            micro, rem = divmod(tbs, gas * dp_world_size)
            if rem:
                raise ConfigError(
                    f"train_batch_size {tbs} is not divisible by "
                    f"grad_accum {gas} * dp_world {dp_world_size}"
                )
        elif tbs is not None and micro is None and gas is None:
            gas = 1
            micro, rem = divmod(tbs, dp_world_size)
            if rem:
                raise ConfigError(
                    f"train_batch_size {tbs} is not divisible by dp_world {dp_world_size}"
                )
        elif tbs is None and micro is not None:
            gas = gas or 1
            tbs = micro * gas * dp_world_size
        elif tbs is None and micro is None:
            raise ConfigError(
                "At least train_batch_size or train_micro_batch_size_per_gpu must be set"
            )

        if tbs != micro * gas * dp_world_size:
            raise ConfigError(
                f"Batch-size triangle violated: train_batch_size ({tbs}) != "
                f"micro ({micro}) * grad_accum ({gas}) * dp_world ({dp_world_size})"
            )
        if tbs <= 0 or micro <= 0 or gas <= 0:
            raise ConfigError("Batch sizes must be positive")

        self.train_batch_size = tbs
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas
        return tbs, micro, gas

    def _validate(self):
        if self.fp16.enabled and self.bf16.enabled:
            raise ConfigError("fp16 and bf16 cannot both be enabled")

    @property
    def mixed_precision_dtype(self):
        if self.fp16.enabled:
            return "float16"
        if self.bf16.enabled:
            return "bfloat16"
        return "float32"


def load_config(config) -> DeepSpeedConfig:
    """Accept a path to a JSON file or an in-memory dict (reference accepts both;
    ``deepspeed/__init__.py:54`` ``config`` / ``config_params``)."""
    if isinstance(config, DeepSpeedConfig):
        return config
    if isinstance(config, str):
        if not os.path.exists(config):
            raise ConfigError(f"DeepSpeed config file not found: {config}")
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise ConfigError(f"config must be a dict or JSON path, got {type(config)}")
    return DeepSpeedConfig.from_dict(config)
