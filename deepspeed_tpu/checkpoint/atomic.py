"""Atomic checkpoint commit protocol + corruption-recovery primitives.

The durability contract (the reference ``NebulaCheckpointEngine``'s
create/save/commit made concrete on a filesystem):

1. Every save stages into ``<tag>.tmp/`` — never into the final tag dir.
2. The stage dir gets a ``COMMITTED`` marker: per-file sizes + CRC32s,
   per-array CRC32s, and step/mesh metadata. Files and the marker are
   fsynced before publication.
3. ``os.replace(<tag>.tmp, <tag>)`` publishes the tag — the rename is the
   commit point; readers never observe a half-written tag dir.
4. The ``latest`` pointer is its own atomic swap (``latest.tmp`` +
   ``os.replace``) and is only advanced after the tag is durable.

A crash at any point leaves either (a) a stale ``.tmp`` dir and an
untouched ``latest``, or (b) a fully-committed tag. ``resume_candidates``
plus ``verify_checkpoint_dir`` implement the recovery walk: newest first,
quarantining anything that fails verification to ``<tag>.corrupt``.

Fault-injection seam: all file writes funnel through ``write_bytes`` /
``write_npz`` / ``write_json``, which call :func:`fault_point` before and
after touching the disk. ``deepspeed_tpu.testing.fault_injection``
registers hooks here to deterministically fail or truncate the Nth write.
"""

import json
import os
import shutil
import zlib
from types import MappingProxyType

import numpy as np

from ..utils.logging import logger

MARKER = "COMMITTED"
TMP_SUFFIX = ".tmp"
CORRUPT_SUFFIX = ".corrupt"
MARKER_VERSION = 1


class CheckpointError(RuntimeError):
    """Base class for checkpoint durability failures."""


#: Falsy sentinel for a COMMITTED file that exists but cannot be parsed.
#: Distinct from ``None`` (marker absent = pre-protocol save): torn marker
#: bytes are proof of damage, not of age. Falsy + read-only mapping so
#: ``if marker`` and ``marker.get(...)`` both behave for defensive callers.
CORRUPT_MARKER = MappingProxyType({})


class CheckpointCorruptionError(CheckpointError, ValueError):
    """A committed checkpoint failed marker/checksum verification.

    Subclasses ``ValueError`` so pre-protocol callers that caught shape /
    coverage errors as ``ValueError`` keep working.
    """


class TornWriteError(CheckpointError, OSError):
    """Staged bytes changed between write and marker sealing. The attempt is
    invalid but a fresh re-stage may well succeed, so this subclasses
    ``OSError`` to be retryable by the save-path policies (every retry cuts
    a fresh stage dir)."""


# ---------------------------------------------------------------------------
# Fault-injection seam
# ---------------------------------------------------------------------------
_FAULT_HOOKS = []


def register_fault_hook(fn):
    """Register ``fn(event, path)`` to run at every fault point. The hook may
    raise (simulating a crash mid-save) or mutate the file at ``path``
    (simulating a torn write). Test-only; no-op overhead when empty."""
    _FAULT_HOOKS.append(fn)


def unregister_fault_hook(fn):
    try:
        _FAULT_HOOKS.remove(fn)
    except ValueError:
        pass


def fault_point(event, path):
    """Events: ``write`` (before a data file write), ``wrote`` (after, file on
    disk but not fsynced), ``replace`` (before the tag-dir commit rename),
    ``latest`` (before the latest-pointer swap)."""
    for hook in list(_FAULT_HOOKS):
        hook(event, path)


# ---------------------------------------------------------------------------
# Low-level durable writes
# ---------------------------------------------------------------------------
def crc32_bytes(data):
    return zlib.crc32(data) & 0xFFFFFFFF


def crc32_file(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path):
    # directory fsync makes the rename itself durable; not supported on some
    # filesystems — degrade silently rather than fail the save
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_bytes(path, data):
    """Durable write. Returns the file's ``{"size", "crc32"}`` (computed from
    the in-memory payload — no read-back) for :func:`write_marker`."""
    fault_point("write", path)
    with open(path, "wb") as f:
        f.write(data)
    fault_point("wrote", path)
    fsync_file(path)
    return {"size": len(data), "crc32": crc32_bytes(data)}


def write_json(path, obj):
    return write_bytes(path, json.dumps(obj, indent=1).encode())


def write_npz(path, arrays):
    """Durable npz write. Returns ``{"size", "crc32"}``; the CRC read-back
    happens right here while the pages are still warm, not in a second full
    pass at marker time (zipfile seeks back to patch headers, so the CRC
    cannot be accumulated while streaming)."""
    fault_point("write", path)
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    fault_point("wrote", path)
    fsync_file(path)
    return {"size": os.path.getsize(path), "crc32": crc32_file(path)}


def write_file_atomic(path, data):
    """tmp + fsync + rename for a single file (the ``latest`` pointer)."""
    tmp = path + TMP_SUFFIX
    write_bytes(tmp, data)
    fault_point("latest" if os.path.basename(path) == "latest" else "replace",
                path)
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


# ---------------------------------------------------------------------------
# Marker
# ---------------------------------------------------------------------------
def write_marker(stage_dir, tag, meta=None, array_crcs=None, file_crcs=None,
                 kind="checkpoint"):
    """Checksum every file currently in ``stage_dir`` and write the COMMITTED
    marker. Call after all data files are staged, before publication.
    ``file_crcs`` carries ``{filename: {"size", "crc32"}}`` captured at write
    time (the ``write_*`` helpers return them) so sealing the marker doesn't
    re-read multi-GB files; entries whose recorded size no longer matches the
    file on disk are distrusted and re-streamed. ``kind="artifact"`` marks a
    durable side product (e.g. a consolidated export) that must never enter
    the resume chain or retention accounting."""
    meta = meta or {}
    file_crcs = file_crcs or {}
    files = {}
    for name in sorted(os.listdir(stage_dir)):
        if name == MARKER or name.endswith(TMP_SUFFIX):
            continue
        full = os.path.join(stage_dir, name)
        if not os.path.isfile(full):
            continue
        size = os.path.getsize(full)
        known = file_crcs.get(name)
        if known is not None:
            if known["size"] != size:
                # the staged bytes are no longer what was written — sealing
                # a CRC of the torn content would mint a "valid" checkpoint
                # full of garbage; fail this attempt (retryable: a fresh
                # re-stage may succeed)
                raise TornWriteError(
                    f"staged file {name} changed size after write "
                    f"({known['size']} -> {size}) — refusing to seal marker")
            files[name] = {"size": size, "crc32": known["crc32"]}
        else:
            files[name] = {"size": size, "crc32": crc32_file(full)}
    marker = {
        "version": MARKER_VERSION,
        "kind": kind,
        "tag": tag,
        "step": meta.get("global_steps", meta.get("step")),
        "mesh": meta.get("mesh"),
        "files": files,
        "arrays": array_crcs or {},
    }
    write_json(os.path.join(stage_dir, MARKER), marker)
    return marker


def read_marker(path):
    """Parse ``<path>/COMMITTED``. Returns the marker dict, ``None`` if the
    file is absent (pre-protocol save), or the falsy :data:`CORRUPT_MARKER`
    sentinel if it exists but cannot be parsed — a torn post-commit write is
    evidence of damage and must NOT masquerade as a legacy checkpoint."""
    marker_path = os.path.join(path, MARKER)
    if not os.path.exists(marker_path):
        return None
    try:
        with open(marker_path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return CORRUPT_MARKER


def verify_checkpoint_dir(path, deep=True, skip_crc=()):
    """Validate a (published or staged) checkpoint dir against its marker.

    Returns ``(ok, reason)``. ``deep=True`` re-checksums every file (names
    in ``skip_crc`` keep only the size check — e.g. ``arrays.npz`` when
    per-array CRCs will be checked after decode anyway); ``deep=False`` only
    checks marker presence and file sizes (cheap — used for retention and
    candidate-ordering decisions).

    A transient I/O error yields ``(False, "unverifiable: ...")`` — see
    :func:`is_transient_verify_failure`; callers must treat that as
    try-again-later, never as proof of corruption.
    """
    if not os.path.isdir(path):
        return False, "missing directory"
    marker = read_marker(path)
    if not marker:  # absent OR present-but-unparseable
        return False, f"missing or unreadable {MARKER} marker"
    for name, info in marker.get("files", {}).items():
        full = os.path.join(path, name)
        try:
            if not os.path.exists(full):
                return False, f"missing file {name}"
            size = os.path.getsize(full)
            if size != info["size"]:
                return False, (f"size mismatch for {name}: "
                               f"{size} != {info['size']} (truncated?)")
            if deep and name not in skip_crc \
                    and crc32_file(full) != info["crc32"]:
                return False, f"crc32 mismatch for {name}"
        except OSError as e:
            # TOCTOU on a shared fs (fsck/another restart renamed the tag
            # mid-check): a verifier that crashes the recovery walk it
            # protects is worse than a skipped candidate
            return False, f"unverifiable: I/O error on {name}: {e}"
    return True, "ok"


def is_transient_verify_failure(reason):
    """True when a verify failure means "could not check" (transient I/O)
    rather than proven corruption — such tags must never be quarantined."""
    return reason.startswith("unverifiable:")


# ---------------------------------------------------------------------------
# Staging / publication
# ---------------------------------------------------------------------------
def stage_dir_for(path):
    return path.rstrip("/") + TMP_SUFFIX


def make_stage_dir(path):
    """Fresh stage dir for a tag (clears leftovers from a crashed save)."""
    stage = stage_dir_for(path)
    if os.path.exists(stage):
        shutil.rmtree(stage)
    os.makedirs(stage)
    return stage


def publish_tag(path):
    """Commit point: rename ``<tag>.tmp`` into place. The stage dir must
    already hold a COMMITTED marker. Re-publishing an existing tag renames
    the old dir aside first (rmtree before the swap would leave a
    checkpoint-sized window with no tag dir while ``latest`` still names
    it); the aside copy carries the ``.tmp`` suffix, so readers and fsck
    treat a crash leftover as a stale stage, never a resume target."""
    stage = stage_dir_for(path)
    fault_point("replace", path)
    old = None
    if os.path.exists(path):
        old = path + ".old" + TMP_SUFFIX
        if os.path.exists(old):
            shutil.rmtree(old)
        os.replace(path, old)
    os.replace(stage, path)
    fsync_dir(os.path.dirname(path) or ".")
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def publish_latest(parent, tag):
    """Atomically swap the ``latest`` pointer to ``tag``."""
    write_file_atomic(os.path.join(parent, "latest"), tag.encode())


def read_latest(parent):
    latest = os.path.join(parent, "latest")
    if not os.path.exists(latest):
        return None
    try:
        with open(latest) as f:
            return f.read().strip() or None
    except OSError:
        return None


# ---------------------------------------------------------------------------
# Recovery walk
# ---------------------------------------------------------------------------
def is_tag_dir(parent, name):
    return (os.path.isdir(os.path.join(parent, name))
            and not name.endswith(TMP_SUFFIX)
            and CORRUPT_SUFFIX not in name)


def list_tags(parent, newest_first=True):
    """Published tag dirs under ``parent``, ordered by marker step (falling
    back to name) — excludes ``.tmp`` stages, ``.corrupt`` quarantine, and
    marker ``kind="artifact"`` dirs (side products like consolidated exports
    are durable but never resume candidates). Marker-less (legacy) and
    unreadable-marker dirs stay listed — the resume walk sorts those out."""
    if not os.path.isdir(parent):
        return []
    entries = []
    for d in os.listdir(parent):
        if not is_tag_dir(parent, d):
            continue
        marker = read_marker(os.path.join(parent, d))
        if marker and marker.get("kind", "checkpoint") != "checkpoint":
            continue
        step = marker.get("step") if marker else None
        entries.append(((step if isinstance(step, (int, float)) else -1, d), d))
    entries.sort(reverse=newest_first)
    return [d for _, d in entries]


def resume_candidates(parent):
    """Tags to try resuming from, best first: the ``latest`` pointer's target
    (if it names an existing tag dir), then every other tag newest-first."""
    latest = read_latest(parent)
    tags = list_tags(parent, newest_first=True)
    if latest is not None and latest in tags:
        tags.remove(latest)
        tags.insert(0, latest)
    elif latest is not None:
        logger.warning(
            "checkpoint 'latest' points at %r which does not exist under %s — "
            "falling back to the newest published tag", latest, parent)
    return tags


def quarantine(path):
    """Move a corrupt checkpoint aside to ``<tag>.corrupt`` (suffixed with a
    counter if that name is taken) so it is never retried but stays around
    for forensics. Returns the quarantine path (or None if gone already —
    including losing the rename race to another process on a shared fs)."""
    if not os.path.exists(path):
        return None
    dest = path + CORRUPT_SUFFIX
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = f"{path}{CORRUPT_SUFFIX}.{n}"
    try:
        os.replace(path, dest)
    except OSError:
        return None  # another rank quarantined it first
    logger.warning("quarantined corrupt checkpoint %s -> %s", path, dest)
    return dest
