from .engine import CheckpointEngine, NpzCheckpointEngine, AsyncCheckpointEngine
from .atomic import (
    CheckpointError,
    CheckpointCorruptionError,
    TornWriteError,
    verify_checkpoint_dir,
    resume_candidates,
    quarantine,
    read_latest,
    list_tags,
)
from .snapshot import GraceBudgeter, SnapshotManager

__all__ = [
    "CheckpointEngine", "NpzCheckpointEngine", "AsyncCheckpointEngine",
    "CheckpointError", "CheckpointCorruptionError", "TornWriteError",
    "verify_checkpoint_dir", "resume_candidates", "quarantine",
    "read_latest", "list_tags", "GraceBudgeter", "SnapshotManager",
]
