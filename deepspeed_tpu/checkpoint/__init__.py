from .engine import CheckpointEngine, NpzCheckpointEngine, AsyncCheckpointEngine

__all__ = ["CheckpointEngine", "NpzCheckpointEngine", "AsyncCheckpointEngine"]
