"""Checkpoint engines.

TPU-native equivalent of the reference's ``runtime/checkpoint_engine/``:
``CheckpointEngine`` ABC (``checkpoint_engine.py:9`` — create/save/load/commit) with a
synchronous npz-backed implementation (standing in for ``TorchCheckpointEngine``) and
an async thread-pool variant (the ``NebulaCheckpointEngine`` role,
``nebula_checkpoint_engine.py:20``).

Layout (one directory per tag):
    <path>/meta.json            — counters, mesh shape, leaf manifest
    <path>/arrays.npz           — all pytree leaves keyed by joined path

Arrays are gathered to host before writing (single-host). The multi-host sharded
layout (per-shard files + universal reshape, reference ``deepspeed/checkpoint/``)
builds on the same manifest format.
"""

import json
import os
import threading

import numpy as np
import jax

from ..utils.logging import logger


def _flatten_with_names(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(
            str(p.key) if hasattr(p, "key") else str(p.idx) if hasattr(p, "idx") else str(p)
            for p in path
        )
        out[key] = leaf
    return out, treedef


class CheckpointEngine:
    """Reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``."""

    def create(self, tag):
        pass

    def save(self, state_tree, path, meta=None):
        raise NotImplementedError

    def load(self, path, template=None, shardings=None):
        raise NotImplementedError

    def commit(self, tag):
        return True


class NpzCheckpointEngine(CheckpointEngine):
    def save(self, state_tree, path, meta=None):
        os.makedirs(path, exist_ok=True)
        named, _ = _flatten_with_names(state_tree)
        host_arrays = {k: np.asarray(jax.device_get(v)) for k, v in named.items()}
        np.savez(os.path.join(path, "arrays.npz"), **host_arrays)
        manifest = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                    for k, v in host_arrays.items()}
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({"meta": meta or {}, "manifest": manifest}, f, indent=1)
        # reference writes a 'latest' file next to the tag dirs (engine.py:2876)
        parent = os.path.dirname(path)
        with open(os.path.join(parent, "latest"), "w") as f:
            f.write(os.path.basename(path))

    def load(self, path, template=None, shardings=None):
        with open(os.path.join(path, "meta.json")) as f:
            blob = json.load(f)
        arrays = np.load(os.path.join(path, "arrays.npz"))
        if template is None:
            return {k: arrays[k] for k in arrays.files}, blob["meta"]
        named_template, treedef = _flatten_with_names(template)
        named_shardings, _ = _flatten_with_names(shardings) if shardings is not None else ({}, None)
        leaves = []
        for key, tmpl in named_template.items():
            if key not in arrays:
                raise KeyError(f"Checkpoint missing array '{key}'")
            arr = arrays[key]
            if tuple(arr.shape) != tuple(tmpl.shape):
                raise ValueError(
                    f"Checkpoint shape mismatch for '{key}': {arr.shape} vs {tmpl.shape}"
                )
            sharding = named_shardings.get(key)
            leaves.append(jax.device_put(arr, sharding) if sharding is not None else arr)
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
        return tree, blob["meta"]


class AsyncCheckpointEngine(NpzCheckpointEngine):
    """Write in a background thread; ``commit`` joins (the Nebula engine's
    commit-based durability contract, ``nebula_checkpoint_engine.py:20``)."""

    def __init__(self):
        self._thread = None

    def save(self, state_tree, path, meta=None):
        # device_get on the caller thread (arrays may be donated right after)
        named, _ = _flatten_with_names(state_tree)
        host = {k: np.asarray(jax.device_get(v)) for k, v in named.items()}

        def write():
            os.makedirs(path, exist_ok=True)
            np.savez(os.path.join(path, "arrays.npz"), **host)
            manifest = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                        for k, v in host.items()}
            with open(os.path.join(path, "meta.json"), "w") as f:
                json.dump({"meta": meta or {}, "manifest": manifest}, f, indent=1)
            parent = os.path.dirname(path)
            with open(os.path.join(parent, "latest"), "w") as f:
                f.write(os.path.basename(path))

        # Serialize with any in-flight save: two writers would race on the shared
        # "latest" pointer and commit() only joins the newest thread.
        if self._thread is not None:
            self._thread.join()
        self._thread = threading.Thread(target=write, daemon=True)
        self._thread.start()

    def commit(self, tag):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        return True
