"""Checkpoint engines.

TPU-native equivalent of the reference's ``runtime/checkpoint_engine/``:
``CheckpointEngine`` ABC (``checkpoint_engine.py:9`` — create/save/load/commit) with a
synchronous npz-backed implementation (standing in for ``TorchCheckpointEngine``) and
an async thread-pool variant (the ``NebulaCheckpointEngine`` role,
``nebula_checkpoint_engine.py:20``).

Layout (one directory per tag):
    <path>/meta.json            — counters, mesh shape, leaf manifest
    <path>/arrays.npz           — all pytree leaves keyed by joined path
    <path>/COMMITTED            — durability marker: per-file + per-array CRC32s

Arrays are gathered to host before writing (single-host). The multi-host sharded
layout (per-shard files + universal reshape, reference ``deepspeed/checkpoint/``)
builds on the same manifest format.

Durability: every save stages into ``<tag>.tmp/`` and only reaches the final
tag name via the atomic commit protocol in ``checkpoint/atomic.py`` — a crash
or injected fault mid-save can never advance the ``latest`` pointer or leave a
half-written tag where a reader will find it.
"""

import json
import os
import threading

import numpy as np
import jax

from ..utils.logging import logger
from ..utils.retry import io_retry_policy, retry_call
from . import atomic
from .atomic import CheckpointCorruptionError, CheckpointError  # noqa: F401 (re-export)


def _flatten_with_names(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(
            str(p.key) if hasattr(p, "key") else str(p.idx) if hasattr(p, "idx") else str(p)
            for p in path
        )
        out[key] = leaf
    return out, treedef


class CheckpointEngine:
    """Reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``."""

    def create(self, tag):
        pass

    def save(self, state_tree, path, meta=None):
        raise NotImplementedError

    def load(self, path, template=None, shardings=None, verify=True):
        raise NotImplementedError

    def commit(self, tag):
        return True


class NpzCheckpointEngine(CheckpointEngine):
    def __init__(self, retry_policy=None):
        self._retry = retry_policy or io_retry_policy()

    def _write_tag(self, host_arrays, path, meta, kind="checkpoint"):
        """Atomic tag commit: stage -> marker -> publish. Runs under retry —
        a fresh stage dir is cut on every attempt. The 'latest' swap is NOT
        part of this unit (see ``_commit_tag``). ``kind="artifact"`` seals a
        side product (e.g. a consolidated copy) that stays out of the resume
        chain and retention accounting entirely."""
        stage = atomic.make_stage_dir(path)
        file_crcs = {"arrays.npz": atomic.write_npz(
            os.path.join(stage, "arrays.npz"), host_arrays)}
        manifest = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                    for k, v in host_arrays.items()}
        file_crcs["meta.json"] = atomic.write_json(
            os.path.join(stage, "meta.json"),
            {"meta": meta or {}, "manifest": manifest})
        # crc32 accepts any contiguous buffer — no tobytes() copy
        array_crcs = {k: atomic.crc32_bytes(np.ascontiguousarray(v))
                      for k, v in host_arrays.items()}
        atomic.write_marker(stage, os.path.basename(path), meta=meta or {},
                            array_crcs=array_crcs, file_crcs=file_crcs,
                            kind=kind)
        atomic.publish_tag(path)

    def _commit_tag(self, host_arrays, path, meta):
        """Full durable save: the tag commit and the 'latest' swap are
        SEPARATE retry units — a transient flake on the ~20-byte pointer
        write must not re-stage and re-publish the multi-GB tag."""
        retry_call(self._write_tag, host_arrays, path, meta,
                   policy=self._retry, describe=f"checkpoint save {path}")
        # reference writes a 'latest' file next to the tag dirs (engine.py:2876)
        retry_call(atomic.publish_latest, os.path.dirname(path),
                   os.path.basename(path), policy=self._retry,
                   describe=f"latest swap {path}")

    def save(self, state_tree, path, meta=None):
        named, _ = _flatten_with_names(state_tree)
        host_arrays = {k: np.asarray(jax.device_get(v)) for k, v in named.items()}
        self._commit_tag(host_arrays, path, meta)

    def load(self, path, template=None, shardings=None, verify=True):
        marker = None
        if verify:
            marker = atomic.read_marker(path)
            if marker is None:
                logger.warning("checkpoint %s has no %s marker (pre-protocol "
                               "save?) — loading unverified", path, atomic.MARKER)
            else:
                # an unreadable marker is the CORRUPT_MARKER sentinel, not
                # None — it reaches verify (which rejects it) instead of
                # masquerading as a pre-protocol save. arrays.npz skips the
                # file-level CRC only when the per-array CRCs (checked after
                # decode below) cover it end-to-end; small files like
                # meta.json are still CRC-verified.
                ok, reason = atomic.verify_checkpoint_dir(
                    path,
                    skip_crc=("arrays.npz",) if marker.get("arrays") else ())
                if not ok:
                    raise CheckpointCorruptionError(
                        f"checkpoint {path} failed verification: {reason}")
        with open(os.path.join(path, "meta.json")) as f:
            blob = json.load(f)
        arrays = np.load(os.path.join(path, "arrays.npz"))

        def check_array(key, arr):
            """End-to-end decode check against the marker's per-array CRCs
            (the file-level CRC can't catch npz-decode corruption)."""
            want = (marker or {}).get("arrays", {}).get(key)
            if want is not None and atomic.crc32_bytes(
                    np.ascontiguousarray(arr)) != want:
                raise CheckpointCorruptionError(
                    f"checkpoint {path}: array '{key}' fails its CRC32 "
                    f"after decode")
            return arr

        if template is None:
            return {k: check_array(k, arrays[k]) for k in arrays.files}, blob["meta"]
        named_template, treedef = _flatten_with_names(template)
        named_shardings, _ = _flatten_with_names(shardings) if shardings is not None else ({}, None)
        leaves = []
        for key, tmpl in named_template.items():
            if key not in arrays:
                raise KeyError(f"Checkpoint missing array '{key}'")
            arr = check_array(key, arrays[key])
            if tuple(arr.shape) != tuple(tmpl.shape):
                raise ValueError(
                    f"Checkpoint shape mismatch for '{key}': {arr.shape} vs {tmpl.shape}"
                )
            sharding = named_shardings.get(key)
            leaves.append(jax.device_put(arr, sharding) if sharding is not None else arr)
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
        return tree, blob["meta"]


class AsyncWriterMixin:
    """Background-writer scaffolding shared by the async engines: one
    in-flight writer thread, its failure captured and re-raised exactly once
    — from ``commit()``, or from the next ``save()`` if commit was skipped —
    so a failed async checkpoint can never be treated as durable."""

    _thread = None
    _error = None
    _commit_err = None

    def _drain(self):
        """Join any in-flight write and surface its failure exactly once.
        ``commit()`` additionally records the failure in ``_commit_err`` so
        a RETRIED commit fails again instead of falsely reporting
        durability; a fresh ``save()`` clears that record."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointError("async checkpoint write failed") from err

    def _spawn_writer(self, fn):
        def run():
            try:
                fn()
            except BaseException as e:  # surfaced at commit / next save
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()


class AsyncCheckpointEngine(AsyncWriterMixin, NpzCheckpointEngine):
    """Write in a background thread; ``commit`` joins and re-raises any
    background failure (the Nebula engine's commit-based durability contract,
    ``nebula_checkpoint_engine.py:20``). A failed async write can never be
    treated as durable: the exception surfaces from ``commit()`` — or from
    the next ``save()`` if the caller skipped commit — and the atomic
    protocol guarantees ``latest`` was not advanced."""

    def save(self, state_tree, path, meta=None):
        # device_get on the caller thread (arrays may be donated right after)
        named, _ = _flatten_with_names(state_tree)
        host = {k: np.asarray(jax.device_get(v)) for k, v in named.items()}

        # Serialize with any in-flight save (two writers would race on the
        # shared "latest" pointer) and re-raise its failure here rather than
        # silently dropping it.
        self._drain()
        self._commit_err = None  # fresh attempt: drop any sticky commit failure
        self._spawn_writer(lambda: self._commit_tag(host, path, meta))

    def commit(self, tag):
        try:
            self._drain()
        except CheckpointError as e:
            self._commit_err = e
            raise
        if self._commit_err is not None:
            raise self._commit_err  # retried commit: still not durable
        return True
