"""Sharded multi-host checkpoint with universal (mesh-shape-agnostic) reload.

TPU-native replacement for the reference's checkpoint tools
(``deepspeed/checkpoint/universal_checkpoint.py:95`` load_hp_checkpoint_state,
``reshape_meg_2d.py:222``, ``reshape_3d_utils.py``, and the consolidated-state
paths ``runtime/engine.py:3127`` / ``utils/zero_to_fp32.py``). The reference
stores per-rank partition files whose layout bakes in the dp/tp/pp sizes, then
needs 1k+ LoC of reshape logic to move between mesh shapes. Here the layout is
*index-range-addressed from day one*:

- save: every process writes ONLY its addressable shards (no gather anywhere),
  as one npz per process; each entry's key encodes the leaf path plus the
  global index range it covers (``leaf@0:128,256:512``). Replicated copies are
  deduplicated by ``shard.replica_id == 0``.
- load: the target sharding (ANY mesh shape) drives assembly through
  ``jax.make_array_from_callback`` — each device's shard is stitched from
  whichever saved pieces intersect its index range. dp 4->2, tp 1->2, pp
  resizes etc. are all the same code path, and no host ever materializes a
  full leaf unless it actually serves a full-leaf shard.
- ``consolidate()``: the offline fp32 tool (``zero_to_fp32.py`` role) that
  assembles plain npz from a sharded directory for export.

Layout (one directory per tag):
    meta.json            — user meta + manifest {leaf: shape/dtype} (process 0)
    pieces-<p>.json      — piece index written by process p
    shards-<p>.npz       — that process's deduplicated shard data
"""

import json
import os
import re

import numpy as np
import jax

from .engine import CheckpointEngine, NpzCheckpointEngine, _flatten_with_names


def _ranges_key(leaf_key, index, shape):
    """leaf path + concrete (start:stop) per dim (slices may have None fields)."""
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        parts.append(f"{start}:{stop}")
    return f"{leaf_key}@{','.join(parts)}"


def _parse_ranges(spec):
    if not spec:
        return ()
    return tuple(tuple(map(int, p.split(":"))) for p in spec.split(","))


class ShardedCheckpointEngine(CheckpointEngine):
    """Per-shard save, reshape-on-load. Works single-process (all devices
    addressable) and multi-host (each process saves/loads its own slice set)."""

    def _prepare(self, state_tree):
        """Device -> host: pull this process's deduplicated shards (must happen
        on the caller thread — the arrays may be donated right after save)."""
        named, _ = _flatten_with_names(state_tree)
        blobs, pieces, manifest = {}, {}, {}
        for key, leaf in named.items():
            arr = jnp_aslike(leaf)
            manifest[key] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
            entries = []
            if hasattr(arr, "addressable_shards") and arr.addressable_shards:
                for shard in arr.addressable_shards:
                    if getattr(shard, "replica_id", 0) != 0:
                        continue  # someone else's identical copy
                    rk = _ranges_key(key, shard.index, arr.shape)
                    blobs[rk] = np.asarray(shard.data)
                    entries.append(rk)
            else:
                rk = _ranges_key(key, tuple(slice(0, d) for d in arr.shape),
                                 arr.shape)
                blobs[rk] = np.asarray(arr)
                entries.append(rk)
            if entries:
                pieces[key] = entries
        return blobs, pieces, manifest

    def _write(self, path, blobs, pieces, manifest, meta):
        proc = jax.process_index()
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, f"shards-{proc}.npz"), **blobs)
        with open(os.path.join(path, f"pieces-{proc}.json"), "w") as f:
            json.dump(pieces, f)
        if proc == 0:
            with open(os.path.join(path, "meta.json"), "w") as f:
                json.dump({"meta": meta or {}, "manifest": manifest,
                           "layout": "sharded"}, f, indent=1)

    def _point_latest(self, path):
        """Repoint 'latest' — only after EVERY process's shards are durable
        (the barrier), or a preempted host leaves 'latest' naming a checkpoint
        whose pieces don't cover the leaves and clobbers the last good one."""
        from .. import comm as dist

        dist.barrier()
        if jax.process_index() == 0:
            parent = os.path.dirname(path)
            with open(os.path.join(parent, "latest"), "w") as f:
                f.write(os.path.basename(path))

    def save(self, state_tree, path, meta=None):
        blobs, pieces, manifest = self._prepare(state_tree)
        self._write(path, blobs, pieces, manifest, meta)
        self._last_path = path

    def commit(self, tag):
        path = getattr(self, "_last_path", None)
        if path is not None:
            self._point_latest(path)
            self._last_path = None
        return True

    # ------------------------------------------------------------------
    def load(self, path, template=None, shardings=None):
        if not os.path.exists(os.path.join(path, "pieces-0.json")):
            # legacy single-file layout
            return NpzCheckpointEngine().load(path, template=template,
                                              shardings=shardings)
        with open(os.path.join(path, "meta.json")) as f:
            blob = json.load(f)

        # piece index across all processes: leaf -> [(ranges, file, npz key)]
        index = {}
        files = {}
        for fn in sorted(os.listdir(path)):
            m = re.match(r"pieces-(\d+)\.json$", fn)
            if not m:
                continue
            p = m.group(1)
            shard_file = os.path.join(path, f"shards-{p}.npz")
            files[shard_file] = np.load(shard_file, mmap_mode=None)
            with open(os.path.join(path, fn)) as f:
                for key, entries in json.load(f).items():
                    for rk in entries:
                        ranges = _parse_ranges(rk.split("@", 1)[1])
                        index.setdefault(key, []).append((ranges, shard_file, rk))

        def read_region(key, starts, stops, shape, dtype):
            """Assemble [starts, stops) of leaf ``key`` from stored pieces."""
            out_shape = tuple(b - a for a, b in zip(starts, stops))
            out = np.empty(out_shape, dtype)
            filled = 0
            for ranges, shard_file, rk in index.get(key, ()):
                src_starts = [r[0] for r in ranges]
                src_stops = [r[1] for r in ranges]
                lo = [max(a, sa) for a, sa in zip(starts, src_starts)]
                hi = [min(b, sb) for b, sb in zip(stops, src_stops)]
                if any(a >= b for a, b in zip(lo, hi)):
                    continue
                src = files[shard_file][rk]
                src_sel = tuple(slice(a - sa, b - sa)
                                for a, b, sa in zip(lo, hi, src_starts))
                dst_sel = tuple(slice(a - oa, b - oa)
                                for a, b, oa in zip(lo, hi, starts))
                out[dst_sel] = src[src_sel]
                filled += int(np.prod([b - a for a, b in zip(lo, hi)]))
            if filled < int(np.prod(out_shape)):
                raise ValueError(
                    f"Checkpoint pieces do not cover '{key}' "
                    f"[{starts}:{stops}) — incomplete checkpoint?")
            return out

        if template is None:
            # full assembly (consolidation path)
            out = {}
            for key, info in blob["manifest"].items():
                shape = tuple(info["shape"])
                out[key] = read_region(key, (0,) * len(shape), shape, shape,
                                       np.dtype(info["dtype"]))
            return out, blob["meta"]

        named_template, treedef = _flatten_with_names(template)
        named_shardings, _ = _flatten_with_names(shardings) \
            if shardings is not None else ({}, None)
        leaves = []
        for key, tmpl in named_template.items():
            info = blob["manifest"].get(key)
            if info is None:
                raise KeyError(f"Checkpoint missing array '{key}'")
            shape = tuple(info["shape"])
            if shape != tuple(tmpl.shape):
                raise ValueError(
                    f"Checkpoint shape mismatch for '{key}': {shape} vs "
                    f"{tuple(tmpl.shape)}")
            dtype = np.dtype(info["dtype"])
            sharding = named_shardings.get(key)
            if sharding is None:
                leaves.append(read_region(key, (0,) * len(shape), shape,
                                          shape, dtype))
                continue

            def cb(idx, _key=key, _shape=shape, _dtype=dtype):
                starts = tuple(0 if s.start is None else s.start for s in idx)
                stops = tuple(d if s.stop is None else s.stop
                              for s, d in zip(idx, _shape))
                return read_region(_key, starts, stops, _shape, _dtype)

            leaves.append(jax.make_array_from_callback(shape, sharding, cb))
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
        return tree, blob["meta"]


class AsyncShardedCheckpointEngine(ShardedCheckpointEngine):
    """Sharded save with the file IO in a background thread; ``commit`` joins,
    re-raises any background failure, THEN repoints 'latest' (the
    Nebula-engine durability contract). The device->host shard pull and all
    collectives stay on the caller thread — donated buffers and multihost sync
    are both thread-unsafe."""

    def __init__(self):
        self._thread = None
        self._error = None

    def _join(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint write failed") from err

    def save(self, state_tree, path, meta=None):
        import threading

        blobs, pieces, manifest = self._prepare(state_tree)
        self._join()  # serialize with (and surface errors from) prior save

        def write():
            try:
                self._write(path, blobs, pieces, manifest, meta)
            except BaseException as e:  # surfaced at commit/next save
                self._error = e

        self._thread = threading.Thread(target=write, daemon=True)
        self._thread.start()
        self._last_path = path

    def commit(self, tag):
        self._join()
        path = getattr(self, "_last_path", None)
        if path is not None:
            self._point_latest(path)
            self._last_path = None
        return True


def jnp_aslike(leaf):
    import jax.numpy as jnp

    return leaf if isinstance(leaf, jax.Array) else jnp.asarray(leaf)


def consolidate(path, out_path=None):
    """Offline consolidation: sharded dir -> plain npz + meta (the
    ``zero_to_fp32.py`` / ``_zero3_consolidated_16bit_state_dict`` role)."""
    arrays, meta = ShardedCheckpointEngine().load(path, template=None)
    out_path = out_path or path + "-consolidated"
    os.makedirs(out_path, exist_ok=True)
    np.savez(os.path.join(out_path, "arrays.npz"), **arrays)
    manifest = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                for k, v in arrays.items()}
    with open(os.path.join(out_path, "meta.json"), "w") as f:
        json.dump({"meta": meta, "manifest": manifest}, f, indent=1)
    return out_path
