"""Sharded multi-host checkpoint with universal (mesh-shape-agnostic) reload.

TPU-native replacement for the reference's checkpoint tools
(``deepspeed/checkpoint/universal_checkpoint.py:95`` load_hp_checkpoint_state,
``reshape_meg_2d.py:222``, ``reshape_3d_utils.py``, and the consolidated-state
paths ``runtime/engine.py:3127`` / ``utils/zero_to_fp32.py``). The reference
stores per-rank partition files whose layout bakes in the dp/tp/pp sizes, then
needs 1k+ LoC of reshape logic to move between mesh shapes. Here the layout is
*index-range-addressed from day one*:

- save: every process writes ONLY its addressable shards (no gather anywhere),
  as one npz per process; each entry's key encodes the leaf path plus the
  global index range it covers (``leaf@0:128,256:512``). Replicated copies are
  deduplicated by ``shard.replica_id == 0``.
- load: the target sharding (ANY mesh shape) drives assembly through
  ``jax.make_array_from_callback`` — each device's shard is stitched from
  whichever saved pieces intersect its index range. dp 4->2, tp 1->2, pp
  resizes etc. are all the same code path, and no host ever materializes a
  full leaf unless it actually serves a full-leaf shard.
- ``consolidate()``: the offline fp32 tool (``zero_to_fp32.py`` role) that
  assembles plain npz from a sharded directory for export.

Layout (one directory per tag):
    meta.json            — user meta + manifest {leaf: shape/dtype} (process 0)
    pieces-<p>.json      — piece index written by process p
    shards-<p>.npz       — that process's deduplicated shard data
"""

import json
import os
import re

import numpy as np
import jax

from ..utils.logging import logger
from ..utils.retry import io_retry_policy, retry_call
from . import atomic
from .atomic import CheckpointCorruptionError, CheckpointError
from .engine import (AsyncWriterMixin, CheckpointEngine, NpzCheckpointEngine,
                     _flatten_with_names)


def _ranges_key(leaf_key, index, shape):
    """leaf path + concrete (start:stop) per dim (slices may have None fields)."""
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        parts.append(f"{start}:{stop}")
    return f"{leaf_key}@{','.join(parts)}"


def _parse_ranges(spec):
    if not spec:
        return ()
    return tuple(tuple(map(int, p.split(":"))) for p in spec.split(","))


class ShardedCheckpointEngine(CheckpointEngine):
    """Per-shard save, reshape-on-load. Works single-process (all devices
    addressable) and multi-host (each process saves/loads its own slice set)."""

    def _prepare(self, state_tree):
        """Device -> host: pull this process's deduplicated shards (must happen
        on the caller thread — the arrays may be donated right after save)."""
        named, _ = _flatten_with_names(state_tree)
        blobs, pieces, manifest = {}, {}, {}
        for key, leaf in named.items():
            arr = jnp_aslike(leaf)
            manifest[key] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
            # pieces entry: {ranges_key: crc32 of the raw shard bytes} — the
            # CRC is checked after npz decode on load (end-to-end), letting
            # verified loads skip a whole-file CRC pass over the shard npzs
            entries = {}
            if hasattr(arr, "addressable_shards") and arr.addressable_shards:
                for shard in arr.addressable_shards:
                    if getattr(shard, "replica_id", 0) != 0:
                        continue  # someone else's identical copy
                    rk = _ranges_key(key, shard.index, arr.shape)
                    blobs[rk] = np.asarray(shard.data)
                    entries[rk] = atomic.crc32_bytes(
                        np.ascontiguousarray(blobs[rk]))
            else:
                rk = _ranges_key(key, tuple(slice(0, d) for d in arr.shape),
                                 arr.shape)
                blobs[rk] = np.asarray(arr)
                entries[rk] = atomic.crc32_bytes(
                    np.ascontiguousarray(blobs[rk]))
            if entries:
                pieces[key] = entries
        return blobs, pieces, manifest

    def __init__(self, retry_policy=None):
        self._retry = retry_policy or io_retry_policy()
        # _finalize cannot cut a fresh stage dir (the premise behind
        # TornWriteError being retryable in atomic.py), so a torn stage is
        # terminal there — retrying would fail identically every attempt
        self._finalize_retry = self._retry.excluding(atomic.TornWriteError)

    def _stage(self, path, blobs, pieces, manifest, meta):
        """Write this process's shards into the ``<tag>.tmp`` stage dir.
        Returns the staged files' write-time CRCs for the marker pass (they
        cover only THIS process's files — ``_finalize`` streams the rest)."""
        proc = jax.process_index()
        stage = atomic.stage_dir_for(path)
        if proc == 0 and jax.process_count() == 1:
            stage = atomic.make_stage_dir(path)
        else:
            os.makedirs(stage, exist_ok=True)
        crcs = {f"shards-{proc}.npz": atomic.write_npz(
            os.path.join(stage, f"shards-{proc}.npz"), blobs)}
        crcs[f"pieces-{proc}.json"] = atomic.write_json(
            os.path.join(stage, f"pieces-{proc}.json"), pieces)
        if proc == 0:
            crcs["meta.json"] = atomic.write_json(
                os.path.join(stage, "meta.json"),
                {"meta": meta or {}, "manifest": manifest,
                 "layout": "sharded"})
        self._stage_crcs = crcs
        return crcs

    def _finalize(self, path, meta):
        """Process 0 only: checksum everything staged, write the COMMITTED
        marker, and atomically publish the tag dir. Shard files from ranks
        beyond the current world size are stale leftovers of a crashed save
        at a larger scale (the multi-process stage dir is reused, not
        cleared) — purge them or the marker would seal old-step data into a
        "valid" checkpoint that load() happily assembles."""
        stage = atomic.stage_dir_for(path)
        if not os.path.isdir(stage):
            # a previous attempt already published this stage and failed
            # later (e.g. at the pointer swap) — a retried commit has
            # nothing left to seal
            if os.path.isdir(path):
                return
            raise CheckpointError(f"no stage or published dir for {path}")
        nproc = jax.process_count()
        for fn in os.listdir(stage):
            m = re.match(r"(?:shards|pieces)-(\d+)\.(?:npz|json)$", fn)
            if m and int(m.group(1)) >= nproc:
                os.remove(os.path.join(stage, fn))
        atomic.write_marker(stage, os.path.basename(path), meta=meta or {},
                            file_crcs=getattr(self, "_stage_crcs", None))
        atomic.publish_tag(path)

    def _point_latest(self, path):
        """Repoint 'latest' — only after EVERY process's shards are durable
        and the tag is published (the barrier), or a preempted host leaves
        'latest' naming a checkpoint whose pieces don't cover the leaves and
        clobbers the last good one. The pointer write is its own retry unit,
        and its outcome is group-fenced: a rank-0 flake must fail EVERY
        rank's commit(), or a caller-level commit retry re-enters _seal's
        collectives on rank 0 alone and deadlocks."""
        from .. import comm as dist

        dist.barrier()
        err = None
        if jax.process_index() == 0:
            try:
                retry_call(atomic.publish_latest, os.path.dirname(path),
                           os.path.basename(path), policy=self._retry,
                           describe=f"latest swap {path}")
            except Exception as e:
                err = e
        if jax.process_count() > 1 and not dist.all_agree(err is None):
            if err is None:
                err = CheckpointError(
                    f"latest swap failed on process 0 for {path}")
        if err is not None:
            raise err

    def _save_local(self, state_tree, path, meta):
        blobs, pieces, manifest = self._prepare(state_tree)
        retry_call(self._stage, path, blobs, pieces, manifest, meta,
                   policy=self._retry, describe=f"sharded stage {path}")
        if jax.process_count() == 1:
            # single-process: the tag is complete the moment our shards are
            # staged — publish immediately so the dir is loadable pre-commit
            retry_call(self._finalize, path, meta,
                       policy=self._finalize_retry,
                       describe=f"sharded publish {path}")
        self._last_meta = meta

    def save(self, state_tree, path, meta=None):
        if jax.process_count() > 1:
            # defer a rank-local stage failure to commit's consensus fence —
            # raising here would strand the other ranks in _seal's collective
            try:
                self._save_local(state_tree, path, meta)
                self._save_err = None
            except Exception as e:
                self._save_err = e
        else:
            self._save_local(state_tree, path, meta)
            self._save_err = None
        self._last_path = path

    def _seal(self, path, local_err=None):
        """Multi-process commit tail. The first consensus IS the staging
        fence: every rank reports its stage outcome (``local_err``) — a rank
        whose write failed joins the collective and fails the whole group
        instead of raising early and stranding everyone else in a barrier.
        Then process 0 seals the tag and ALL ranks agree on that outcome
        before the pointer moves."""
        if jax.process_count() > 1:
            from .. import comm as dist

            if not dist.all_agree(local_err is None):
                if local_err is not None:
                    raise local_err
                raise CheckpointError(
                    f"checkpoint staging failed on another process for {path}")
            # every rank already computed write-time CRCs for its own staged
            # files — ship them to the sealing rank, or write_marker's
            # fallback re-streams every other host's shards over the shared
            # fs and commit cost becomes O(total checkpoint size) on rank 0
            all_crcs = dist.allgather_obj(getattr(self, "_stage_crcs", {}))
            err = None
            if jax.process_index() == 0:
                self._stage_crcs = {name: info for crcs in all_crcs
                                    for name, info in (crcs or {}).items()}
                try:
                    retry_call(self._finalize, path,
                               getattr(self, "_last_meta", None),
                               policy=self._finalize_retry,
                               describe=f"sharded publish {path}")
                except Exception as e:
                    err = e
            if not dist.all_agree(err is None):
                if err is not None:
                    raise err
                raise CheckpointError(
                    f"checkpoint finalize failed on process 0 for {path}")
        elif local_err is not None:
            raise local_err
        self._point_latest(path)

    def commit(self, tag):
        path = getattr(self, "_last_path", None)
        if path is not None:
            # _save_err and _last_path stay set until the seal SUCCEEDS: a
            # retried commit() after a failed stage must fail again (the
            # stage is incomplete — only a fresh save() clears the error),
            # never silently advance 'latest'; after a transient _finalize
            # failure the retry re-seals the intact stage and succeeds.
            self._seal(path, local_err=getattr(self, "_save_err", None))
            self._save_err = None
            self._last_path = None
        return True

    # ------------------------------------------------------------------
    def load(self, path, template=None, shardings=None, verify=True):
        if not os.path.exists(os.path.join(path, "pieces-0.json")):
            # legacy single-file layout
            return NpzCheckpointEngine().load(path, template=template,
                                              shardings=shardings,
                                              verify=verify)
        def _entry_crc_layout():
            """True when the pieces files carry per-entry CRCs (checked
            after decode), so the file-level CRC of the shard npzs is
            redundant — pre-upgrade checkpoints fall back to the file CRC."""
            try:
                with open(os.path.join(path, "pieces-0.json")) as f:
                    return any(isinstance(v, dict)
                               for v in json.load(f).values())
            except (OSError, ValueError):
                return False

        def _verify_dir():
            marker = atomic.read_marker(path)
            if marker is None:
                return None
            skip = tuple(n for n in marker.get("files", {})
                         if n.startswith("shards-")) \
                if _entry_crc_layout() else ()
            return atomic.verify_checkpoint_dir(path, skip_crc=skip)

        if verify:
            if jax.process_count() > 1:
                # Rank 0 decides BOTH marker presence and the deep verdict in
                # one broadcast: per-rank read_marker on a laggy network fs
                # could diverge, leaving some ranks in a collective the
                # others never join — and per-rank deep verification would
                # read the whole checkpoint P times anyway.
                from .. import comm as dist

                res = _verify_dir() if jax.process_index() == 0 else None
                res = dist.broadcast_obj(res, src=0)
            else:
                res = _verify_dir()
            if res is None:
                logger.warning("checkpoint %s has no %s marker (pre-protocol "
                               "save?) — loading unverified", path, atomic.MARKER)
            else:
                ok, reason = res
                if not ok:
                    raise CheckpointCorruptionError(
                        f"checkpoint {path} failed verification: {reason}")
        with open(os.path.join(path, "meta.json")) as f:
            blob = json.load(f)

        # piece index across all processes:
        #   leaf -> [(ranges, file, npz key, expected crc32-or-None)]
        # (legacy pieces files carry plain lists — no per-entry CRCs)
        index = {}
        files = {}
        for fn in sorted(os.listdir(path)):
            m = re.match(r"pieces-(\d+)\.json$", fn)
            if not m:
                continue
            p = m.group(1)
            shard_file = os.path.join(path, f"shards-{p}.npz")
            files[shard_file] = np.load(shard_file, mmap_mode=None)
            with open(os.path.join(path, fn)) as f:
                for key, entries in json.load(f).items():
                    for rk in entries:
                        ranges = _parse_ranges(rk.split("@", 1)[1])
                        crc = entries[rk] if isinstance(entries, dict) else None
                        index.setdefault(key, []).append(
                            (ranges, shard_file, rk, crc))
        checked_pieces = set()

        def checked(shard_file, rk, crc):
            """End-to-end decode check of one stored piece against its
            pieces-index CRC (once per piece — pieces are reused across
            regions). This is what lets verified loads skip the redundant
            whole-file CRC pass over the shard npzs."""
            src = files[shard_file][rk]
            if verify and crc is not None \
                    and (shard_file, rk) not in checked_pieces:
                if atomic.crc32_bytes(np.ascontiguousarray(src)) != crc:
                    raise CheckpointCorruptionError(
                        f"checkpoint {path}: piece '{rk}' fails its CRC32 "
                        f"after decode")
                checked_pieces.add((shard_file, rk))
            return src

        def read_region(key, starts, stops, shape, dtype):
            """Assemble [starts, stops) of leaf ``key`` from stored pieces."""
            out_shape = tuple(b - a for a, b in zip(starts, stops))
            out = np.empty(out_shape, dtype)
            filled = 0
            for ranges, shard_file, rk, crc in index.get(key, ()):
                src_starts = [r[0] for r in ranges]
                src_stops = [r[1] for r in ranges]
                lo = [max(a, sa) for a, sa in zip(starts, src_starts)]
                hi = [min(b, sb) for b, sb in zip(stops, src_stops)]
                if any(a >= b for a, b in zip(lo, hi)):
                    continue
                src = checked(shard_file, rk, crc)
                src_sel = tuple(slice(a - sa, b - sa)
                                for a, b, sa in zip(lo, hi, src_starts))
                dst_sel = tuple(slice(a - oa, b - oa)
                                for a, b, oa in zip(lo, hi, starts))
                out[dst_sel] = src[src_sel]
                filled += int(np.prod([b - a for a, b in zip(lo, hi)]))
            if filled < int(np.prod(out_shape)):
                raise ValueError(
                    f"Checkpoint pieces do not cover '{key}' "
                    f"[{starts}:{stops}) — incomplete checkpoint?")
            return out

        if template is None:
            # full assembly (consolidation path)
            out = {}
            for key, info in blob["manifest"].items():
                shape = tuple(info["shape"])
                out[key] = read_region(key, (0,) * len(shape), shape, shape,
                                       np.dtype(info["dtype"]))
            return out, blob["meta"]

        named_template, treedef = _flatten_with_names(template)
        named_shardings, _ = _flatten_with_names(shardings) \
            if shardings is not None else ({}, None)
        leaves = []
        for key, tmpl in named_template.items():
            info = blob["manifest"].get(key)
            if info is None:
                raise KeyError(f"Checkpoint missing array '{key}'")
            shape = tuple(info["shape"])
            if shape != tuple(tmpl.shape):
                raise ValueError(
                    f"Checkpoint shape mismatch for '{key}': {shape} vs "
                    f"{tuple(tmpl.shape)}")
            dtype = np.dtype(info["dtype"])
            sharding = named_shardings.get(key)
            if sharding is None:
                leaves.append(read_region(key, (0,) * len(shape), shape,
                                          shape, dtype))
                continue

            def cb(idx, _key=key, _shape=shape, _dtype=dtype):
                starts = tuple(0 if s.start is None else s.start for s in idx)
                stops = tuple(d if s.stop is None else s.stop
                              for s, d in zip(idx, _shape))
                return read_region(_key, starts, stops, _shape, _dtype)

            leaves.append(jax.make_array_from_callback(shape, sharding, cb))
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
        return tree, blob["meta"]


class AsyncShardedCheckpointEngine(AsyncWriterMixin, ShardedCheckpointEngine):
    """Sharded save with the file IO in a background thread; ``commit`` joins,
    re-raises any background failure, THEN repoints 'latest' (the
    Nebula-engine durability contract). The device->host shard pull and all
    collectives stay on the caller thread — donated buffers and multihost sync
    are both thread-unsafe."""

    def save(self, state_tree, path, meta=None):
        blobs, pieces, manifest = self._prepare(state_tree)
        # serialize with (and surface errors from) the prior save. Multi-host:
        # a rank-local raise here would strand the other ranks in _seal's
        # collectives (they save fine and enter commit), so the failure is
        # deferred to the next commit's consensus fence instead — the
        # contract holds: a failed async checkpoint is never reported durable
        if jax.process_count() > 1:
            try:
                self._drain()
                self._save_err = None
            except Exception as e:
                self._save_err = e
        else:
            self._drain()
            self._save_err = None  # fresh attempt: drop sticky commit failure

        def write():
            retry_call(self._stage, path, blobs, pieces, manifest, meta,
                       policy=self._retry,
                       describe=f"async sharded stage {path}")
            if jax.process_count() == 1:
                retry_call(self._finalize, path, meta,
                           policy=self._finalize_retry,
                           describe=f"async sharded publish {path}")

        self._spawn_writer(write)
        self._last_path = path
        self._last_meta = meta

    def commit(self, tag):
        # a local background failure joins the group consensus in _seal
        # instead of raising pre-fence and stranding the other ranks; it is
        # recorded sticky (like a sync stage failure) so a RETRIED commit
        # fails again instead of sealing the incomplete stage
        try:
            self._drain()
        except Exception as e:
            if getattr(self, "_last_path", None) is None:
                raise
            self._save_err = e
        return super().commit(tag)


def jnp_aslike(leaf):
    import jax.numpy as jnp

    return leaf if isinstance(leaf, jax.Array) else jnp.asarray(leaf)


def consolidate(path, out_path=None):
    """Offline consolidation: sharded dir -> plain npz + meta (the
    ``zero_to_fp32.py`` / ``_zero3_consolidated_16bit_state_dict`` role)."""
    arrays, meta = ShardedCheckpointEngine().load(path, template=None)
    out_path = out_path or path + "-consolidated"
    # the full npz commit sequence incl. per-array CRCs, minus the 'latest'
    # swap — a consolidated side artifact must not become the resume target
    NpzCheckpointEngine()._write_tag(arrays, out_path, meta, kind="artifact")
    return out_path
