"""Overlapped step-state snapshots + grace-window flush for elastic training.

The preemption-native checkpoint path (ROADMAP item 5): instead of stopping
the train loop every ``save_interval`` steps for a synchronous save, the
:class:`SnapshotManager` keeps a **double-buffered host shadow** of the full
step state (params / optimizer / loss-scale / rng / counters, via
``engine.capture_step_state``) and drains it to published sharded tags in a
background writer thread:

- ``capture`` (every ``elastic.snapshot_interval`` steps, between step
  dispatches): issue ``copy_to_host_async`` on every addressable shard in one
  pass, then materialize the deduplicated host shards (the sharded engine's
  ``_prepare``). No file I/O happens on the step path, and the capture must
  complete before the next dispatch anyway — the step functions donate the
  very buffers being read.
- background **writer**: stages and publishes the shadow as a normal
  ``<prefix>-step<N>`` tag (full COMMITTED marker — every snapshot is a valid
  resume candidate the moment it is published). Freshest-wins: if the writer
  is still busy when a new shadow lands, the waiting shadow is replaced, so
  at most one write is ever queued.
- ``flush`` (SIGTERM / end of run): join the in-flight write, write the
  **not-yet-written remainder** (only if a fresher shadow was waiting — never
  a from-scratch save), then swap the ``latest`` pointer. Worst case is one
  snapshot write + a ~20-byte pointer swap, which is what the grace budgeter
  sizes against.

The :class:`GraceBudgeter` measures real write+fsync time per snapshot and
step time between captures, stretches the capture cadence when the writer
cannot keep up (within ``[snapshot_interval, max_interval]``), and fires a
once-per-run warning when a flush estimate no longer fits
``grace_period_s / safety_factor`` — observable headroom
(``Elastic/grace_margin_ms``), not assumed.

Clocks are pluggable (``serving.clock.VirtualClock``) so every budgeter
policy is assertable in tier-1 without real sleeps.
"""

import math
import os
import threading

from ..utils.logging import logger
from . import atomic
from .sharded import ShardedCheckpointEngine


class _WallClock:
    """now()-only wall clock. Deliberately NOT serving.clock.WallClock:
    importing it would execute the serving package __init__ (ServingEngine
    -> inference engine) at checkpoint-package import time — a cycle. The
    budgeter only ever calls now()."""

    def now(self):
        import time

        return time.perf_counter()


class GraceBudgeter:
    """Measured flush-time vs grace-window accounting.

    ``record_write`` feeds real write+fsync durations; ``record_step`` feeds
    step durations (EWMA). ``flush_estimate_s`` is the conservative (max of
    the recent window) time one snapshot write takes — the worst-case SIGTERM
    flush. ``effective_interval`` stretches the capture cadence so the writer
    drains between captures instead of piling freshest-wins drops.
    """

    def __init__(self, cfg):
        self.grace_s = float(cfg.grace_period_s)
        self.safety = float(cfg.safety_factor)
        self.base_interval = int(cfg.snapshot_interval)
        self.max_interval = int(cfg.max_interval)
        self._writes = []  # trailing window of write durations (seconds)
        self._step_ewma = None
        self._warned = False
        self.warnings = 0

    def record_write(self, seconds):
        self._writes.append(float(seconds))
        del self._writes[:-32]

    def record_step(self, seconds):
        s = float(seconds)
        self._step_ewma = s if self._step_ewma is None \
            else 0.8 * self._step_ewma + 0.2 * s

    def flush_estimate_s(self):
        return max(self._writes) if self._writes else 0.0

    def grace_margin_s(self):
        """Headroom left in the grace window after a worst-case flush (with
        the safety factor applied). Negative = a preemption may tear."""
        return self.grace_s - self.flush_estimate_s() * self.safety

    def effective_interval(self):
        """Capture cadence: at least ``snapshot_interval``, stretched so one
        write fits between captures (ceil(write / step_time)), capped at
        ``max_interval`` — beyond the cap the writer simply skips shadows
        (freshest-wins) rather than lying about the lost-work bound."""
        if not self._writes or not self._step_ewma:
            return self.base_interval
        keep_up = math.ceil(self.flush_estimate_s()
                            / max(self._step_ewma, 1e-9))
        return max(self.base_interval, min(keep_up, self.max_interval))

    def check(self, step):
        """Once-per-run warning when the measured flush no longer fits the
        grace window; returns the margin either way (for ``Elastic/*``)."""
        margin = self.grace_margin_s()
        if margin < 0 and not self._warned:
            self._warned = True
            self.warnings += 1
            logger.warning(
                "elastic: measured snapshot flush %.1f ms x safety %.1f "
                "exceeds the %.1f ms preemption grace window — a SIGTERM "
                "may arrive mid-write; shrink the state, raise "
                "elastic.grace_period_s, or speed up checkpoint storage",
                self.flush_estimate_s() * 1e3, self.safety,
                self.grace_s * 1e3)
        return margin


class SnapshotManager:
    """Double-buffered host shadow + background sharded writer + budgeter.

    Single-process multi-device today (the tier-1 rig): every snapshot tag is
    published with a full marker the moment the writer finishes, so the
    recovery chain can resume from it even if the final ``latest`` swap never
    happened. Multi-process jobs keep using the agent's synchronous
    ``save_checkpoint`` path (the async commit would need the cross-rank
    consensus fence on the signal path — out of scope here).
    """

    def __init__(self, engine, save_dir, *, cfg, tag_prefix="elastic",
                 clock=None):
        import jax

        if jax.process_count() > 1:
            raise NotImplementedError(
                "SnapshotManager is single-process (multi-host elastic "
                "flush needs the commit consensus fence on the signal path)")
        self.engine = engine
        self.save_dir = save_dir
        self.cfg = cfg
        self.tag_prefix = tag_prefix
        self.clock = clock or _WallClock()
        self.budget = GraceBudgeter(cfg)
        self._io = ShardedCheckpointEngine(
            getattr(engine.checkpoint_engine, "_retry", None))
        self._lock = threading.Lock()
        self._writer = None         # in-flight writer thread
        self._writer_err = None     # last background failure (sticky til flush)
        self._pending = None        # freshest captured-but-unwritten shadow
        self._writing_tag = None    # tag the live writer owns right now
        self._written_step = None   # newest fully PUBLISHED snapshot step
        self._committed_step = None  # newest step 'latest' points at
        self._last_capture_step = None
        self._last_step_t = None
        self.stats = {"snapshots": 0, "writes": 0, "dropped_shadows": 0,
                      "flushes": 0, "flush_ms": [], "write_ms": []}

    # -- capture --------------------------------------------------------------
    def _issue_d2h(self, state_tree):
        """One pass starting every shard's device-to-host copy before any is
        read — the copies overlap each other (and, on an async backend, the
        tail of the step) instead of serializing at np.asarray time.

        Skipped on the CPU backend: host-to-host "transfers" are synchronous
        there (no overlap to win), and on jaxlib 0.4.x ``copy_to_host_async``
        against buffers produced by warm-compile-cache-deserialized
        executables is in the PR 3 crash class."""
        import jax

        if jax.default_backend() == "cpu":
            return

        def issue(leaf):
            if not isinstance(leaf, jax.Array):
                return
            try:
                if hasattr(leaf, "addressable_shards") \
                        and leaf.addressable_shards:
                    for shard in leaf.addressable_shards:
                        if getattr(shard, "replica_id", 0) == 0:
                            shard.data.copy_to_host_async()
                else:
                    leaf.copy_to_host_async()
            except Exception:
                pass  # best-effort prefetch; _prepare's read is authoritative

        jax.tree_util.tree_map(issue, state_tree)

    def maybe_snapshot(self, client_state=None):
        """Per-step hook (call right after ``train_batch``). Captures on the
        budgeted cadence, feeds the step-time EWMA, emits ``Elastic/*``."""
        now = self.clock.now()
        if self._last_step_t is not None:
            self.budget.record_step(now - self._last_step_t)
        self._last_step_t = now
        step = self.engine.global_steps
        interval = self.budget.effective_interval()
        if self._last_capture_step is not None \
                and step - self._last_capture_step < interval:
            self._emit(step)
            return False
        self.capture(client_state)
        self._emit(step)
        return True

    def capture(self, client_state=None):
        """Pull the deduplicated host shards of the live step state into a
        shadow and hand it to the writer (or park it as pending)."""
        step = self.engine.global_steps
        with self.engine.tracer.span("checkpoint/snapshot", cat="checkpoint",
                                     step=step):
            state, meta = self.engine.capture_step_state(client_state)
            self._issue_d2h(state)
            blobs, pieces, manifest = self._io._prepare(state)
        shadow = (step, blobs, pieces, manifest, meta)
        self._last_capture_step = step
        self.stats["snapshots"] += 1
        with self._lock:
            if self._pending is not None:
                # freshest-wins in BOTH branches: a parked shadow orphaned by
                # a failed write must never be resurrected after this newer
                # one (it would regress _written_step and point 'latest'
                # backwards at flush)
                self.stats["dropped_shadows"] += 1
                self._pending = None
            if self._writer is not None and self._writer.is_alive():
                self._pending = shadow
                return
            self._start_write(shadow)

    # -- background writer ----------------------------------------------------
    def _tag(self, step):
        return f"{self.tag_prefix}-step{step}"

    def _start_write(self, shadow):
        # caller holds self._lock
        self._writing_tag = self._tag(shadow[0])
        self._writer = threading.Thread(
            target=self._write, args=(shadow,), daemon=True)
        self._writer.start()

    def _write(self, shadow):
        step, blobs, pieces, manifest, meta = shadow
        path = os.path.join(self.save_dir, self._tag(step))
        t0 = self.clock.now()
        try:
            with self.engine.tracer.span("checkpoint/snapshot_write",
                                         cat="checkpoint", step=step):
                self._io._stage(path, blobs, pieces, manifest, meta)
                self._io._finalize(path, meta)
        except BaseException as e:
            self._writer_err = e
            return
        finally:
            dt = self.clock.now() - t0
            self.budget.record_write(dt)
            self.stats["write_ms"].append(dt * 1e3)
        self.stats["writes"] += 1
        self._writer_err = None  # a newer successful write heals older ones
        with self._lock:
            # monotone: a write completing out of order (a stale shadow that
            # slipped through) must never regress the freshest published step
            advanced = self._written_step is None or step > self._written_step
            if advanced:
                self._written_step = step
            self._writing_tag = None
            nxt, self._pending = self._pending, None
            if nxt is not None:
                if nxt[0] > step:
                    self._start_write(nxt)
                else:
                    self.stats["dropped_shadows"] += 1
        if advanced:
            # commit as we go: the tag is fully durable (staged + fsynced +
            # marker + publish), so advancing 'latest' here makes every
            # snapshot count toward keep_last retention immediately — tags
            # no longer pile up uncommitted between periodic flushes (only
            # the remainder window stays protected from pruning). A flake
            # on the ~20-byte swap is left for flush to retry.
            try:
                atomic.publish_latest(self.save_dir, self._tag(step))
                self._committed_step = step
            except OSError as e:
                logger.warning("elastic: snapshot latest swap failed (%s) — "
                               "the next flush retries it", e)

    def _drain(self):
        while True:
            with self._lock:
                w = self._writer
            if w is None or not w.is_alive():
                # one more pending shadow may have been promoted to a live
                # writer between checks — loop until genuinely idle
                with self._lock:
                    if self._writer is w or self._writer is None:
                        break
                continue
            w.join()

    # -- flush (the grace-window path) ----------------------------------------
    def finalize(self, reason="final"):
        """End-of-run commit: capture the live state if the cadence skipped
        it (the run's last step must never be lost), then flush."""
        if self._last_capture_step != self.engine.global_steps:
            self.capture()
        return self.flush(reason)

    def flush(self, reason="flush"):
        """Commit the freshest shadow: join the in-flight write, write only
        the not-yet-written remainder, swap ``latest``. Returns the committed
        ``(tag, step)`` or ``None`` when nothing was ever captured."""
        step = self.engine.global_steps
        t0 = self.clock.now()
        with self.engine.tracer.span("checkpoint/flush", cat="checkpoint",
                                     reason=reason, step=step):
            self._drain()
            err, self._writer_err = self._writer_err, None
            with self._lock:
                remainder, self._pending = self._pending, None
            if remainder is None and err is not None:
                # the freshest shadow's background write failed and nothing
                # newer was waiting: that shadow IS the remainder — rebuild
                # it from its tag (the stage is torn; re-stage from memory is
                # gone) by re-raising so the agent falls back to a sync save
                raise atomic.CheckpointError(
                    "elastic flush: background snapshot write failed and no "
                    "fresher shadow is available") from err
            if remainder is not None and (
                    self._written_step is None
                    or remainder[0] > self._written_step):
                # the writer fell behind (or died): stage the remainder NOW —
                # still from the already-captured host shadow, never a fresh
                # device pull (a remainder no newer than what's published is
                # just dropped)
                rstep, blobs, pieces, manifest, meta = remainder
                path = os.path.join(self.save_dir, self._tag(rstep))
                t_w = self.clock.now()
                self._io._stage(path, blobs, pieces, manifest, meta)
                self._io._finalize(path, meta)
                self.budget.record_write(self.clock.now() - t_w)
                self.stats["writes"] += 1
                self._written_step = rstep
            if self._written_step is None:
                return None
            if self._committed_step != self._written_step:
                tag = self._tag(self._written_step)
                atomic.publish_latest(self.save_dir, tag)
                self._committed_step = self._written_step
        dt = self.clock.now() - t0
        self.stats["flushes"] += 1
        self.stats["flush_ms"].append(dt * 1e3)
        margin = self.budget.check(step)
        self._monitor_events(
            [("Elastic/flush_ms", dt * 1e3, step),
             ("Elastic/grace_margin_ms", margin * 1e3, step)])
        return self._tag(self._committed_step), self._committed_step

    # -- telemetry ------------------------------------------------------------
    def _emit(self, step):
        age = step - (self._last_capture_step
                      if self._last_capture_step is not None else 0)
        self._monitor_events(
            [("Elastic/snapshot_age_steps", float(age), step),
             ("Elastic/snapshots", float(self.stats["snapshots"]), step),
             ("Elastic/grace_margin_ms",
              self.budget.grace_margin_s() * 1e3, step)])

    def _monitor_events(self, events):
        mon = getattr(self.engine, "monitor", None)
        if mon is not None and getattr(mon, "enabled", False):
            mon.write_events(events)

    @property
    def committed_step(self):
        return self._committed_step

    @property
    def live_tags(self):
        """Tags the writer currently owns (never prune these)."""
        with self._lock:
            tags = set()
            if self._writing_tag:
                tags.add(self._writing_tag)
            if self._pending is not None:
                tags.add(self._tag(self._pending[0]))
            return tags

    def close(self):
        self._drain()
